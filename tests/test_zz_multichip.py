"""Scale-out tests (ISSUE 7): P=1 vs P=8 bit-parity, capacity
negotiation, the skew re-stage, deterministic mesh order and the
SORT_DEVICES knob.

Named ``test_zz_*`` to sort LATE in the tier-1 run — the suite is
timeout-bound and these tests pay fresh shard_map compiles.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from mpitest_tpu.models import api
from mpitest_tpu.models.api import sort
from mpitest_tpu.models.supervisor import SortSupervisor
from mpitest_tpu.ops.keys import codec_for
from mpitest_tpu.parallel.mesh import make_mesh
from mpitest_tpu.utils import knobs
from mpitest_tpu.utils.trace import Tracer

ALGOS = ("radix", "sample")


@pytest.fixture(scope="module")
def mesh1():
    return make_mesh(1)


def _keys(dtype, n, rng):
    dtype = np.dtype(dtype)
    if dtype.kind == "f":
        return (rng.random(n) * 1e6 - 5e5).astype(dtype)
    info = np.iinfo(dtype)
    return rng.integers(info.min, info.max, size=n, dtype=dtype,
                        endpoint=False)


# ---------------------------------------------------- 1-vs-8 bit parity

@pytest.mark.parametrize("algo", ALGOS)
@pytest.mark.parametrize("dtype", [np.int32, np.uint64, np.float32])
def test_parity_1_vs_8_bitwise(algo, dtype, mesh8, mesh1, rng):
    """The sharded sort's output is canonical: 8 devices and 1 device
    must produce the same BYTES, not just the same values."""
    x = _keys(dtype, 2048, rng)
    out8 = sort(x, algorithm=algo, mesh=mesh8)
    out1 = sort(x, algorithm=algo, mesh=mesh1)
    assert out8.dtype == out1.dtype == np.dtype(dtype)
    assert out8.tobytes() == out1.tobytes()


@pytest.mark.parametrize("algo", ALGOS)
@pytest.mark.parametrize("n", [3, 1001])
def test_parity_awkward_n(algo, n, mesh8, mesh1, rng):
    """N < P and P∤N — the padding/slicing contract must hold at any
    mesh size (the reference gets exactly this wrong)."""
    x = rng.integers(-2**31, 2**31 - 1, size=n, dtype=np.int32)
    out8 = sort(x, algorithm=algo, mesh=mesh8)
    out1 = sort(x, algorithm=algo, mesh=mesh1)
    assert out8.tobytes() == out1.tobytes()


# ------------------------------------- negotiation + re-stage behavior

def test_negotiation_sizes_cap_exactly_on_skew(mesh8, rng):
    """Single-pass radix on a sorted (clustered) input: the probe must
    re-stage, negotiate a cap strictly below the worst case, and finish
    with ZERO overflow retries even under a degenerate cap_factor."""
    x = np.sort(rng.integers(0, 1 << 16, size=1 << 13).astype(np.int32))
    t = Tracer()
    out = sort(x, algorithm="radix", mesh=mesh8, digit_bits=16,
               cap_factor=1e-9, tracer=t)
    assert np.array_equal(out, x)
    c = t.counters
    assert c.get("skew_restage") == 1
    assert c["negotiated_cap"] < c["worst_cap"]
    assert c.get("exchange_retries", 0) == 0
    # post-re-stage the exchange is balanced
    assert c["exchange_peer_ratio"] < 2.0
    assert c["exchange_balance_ratio"] < 2.0


def test_regrow_loop_still_carries_negotiation_off(mesh8, rng,
                                                   monkeypatch):
    """With SORT_NEGOTIATE=off the pre-ISSUE-7 behavior is intact: the
    squeezed cap overflows, the regrow loop recovers, output exact."""
    monkeypatch.setenv("SORT_NEGOTIATE", "off")
    x = np.sort(rng.integers(0, 1 << 16, size=1 << 13).astype(np.int32))
    t = Tracer()
    out = sort(x, algorithm="radix", mesh=mesh8, digit_bits=16,
               cap_factor=1e-9, tracer=t)
    assert np.array_equal(out, x)
    assert t.counters.get("exchange_retries", 0) >= 1
    assert "negotiated_cap" not in t.counters


def test_restage_off_keeps_worst_case_cap(mesh8, rng, monkeypatch):
    """SORT_RESTAGE=off: negotiation still sizes the cap (no overflow
    retries), but the clustered arrangement keeps its near-worst-case
    per-peer need — the saving the re-stage exists to claw back."""
    monkeypatch.setenv("SORT_RESTAGE", "off")
    x = np.sort(rng.integers(0, 1 << 16, size=1 << 13).astype(np.int32))
    t = Tracer()
    out = sort(x, algorithm="radix", mesh=mesh8, digit_bits=16, tracer=t)
    assert np.array_equal(out, x)
    c = t.counters
    assert "skew_restage" not in c
    assert c.get("exchange_retries", 0) == 0
    assert c["exchange_peer_ratio"] > 4.0  # diag-heavy: ~P x fair share


def test_exchange_balance_event_schema(mesh8, rng):
    """The exchange_balance event carries per-rank send/recv byte lists
    (one entry per rank) and the negotiated/worst caps."""
    x = rng.integers(-2**31, 2**31 - 1, size=1 << 12, dtype=np.int32)
    t = Tracer()
    sort(x, algorithm="radix", mesh=mesh8, tracer=t)
    ev = [s for s in t.spans.spans if s.name == "exchange_balance"]
    assert len(ev) == 1
    a = ev[0].attrs
    assert len(a["send_bytes"]) == 8 and len(a["recv_bytes"]) == 8
    assert a["negotiated_cap"] <= a["worst_cap"]
    assert a["exact"] is True  # the radix probe is exact


def test_supervisor_reactive_restage_once():
    """exchange_loop invokes re_stage exactly once, at the second
    overflow (persistent imbalance), never on the first."""
    calls: list[int] = []

    def attempt(c):
        # overflows until the re-stage lands, then fits
        return ("ok", c) if calls else ("overflow", c + 1)

    def re_stage():
        calls.append(1)

    sup = SortSupervisor(Tracer())
    payload, cap = sup.exchange_loop(
        "t", attempt, 4, 1, lambda v, a: v, re_stage=re_stage)
    assert payload == "ok" and calls == [1]
    assert sup.tracer.counters["exchange_retries"] == 2


def test_radix_probe_counts_exact(mesh8, rng):
    """Probe invariants: every rank sends all n keys (row sums = n) and
    — radix being receive-balanced by construction — every rank also
    receives exactly n (column sums = n)."""
    x = rng.integers(-2**31, 2**31 - 1, size=1 << 12, dtype=np.int32)
    codec = codec_for(np.dtype(np.int32))
    n = x.size // 8
    words = api._shard_input(codec.encode(x), mesh8, n)
    cnts = np.asarray(
        api._compile_radix_probe(mesh8, 1, n, 8)(*words))
    assert cnts.shape == (8, 8)
    assert (cnts.sum(axis=1) == n).all()
    assert (cnts.sum(axis=0) == n).all()


# ------------------------------- mesh determinism + SORT_DEVICES knob

def test_make_mesh_order_deterministic():
    """Shard↔rank assignment must not depend on enumeration order:
    a shuffled device list yields the same mesh as the sorted one."""
    devs = list(jax.devices())
    ids = [d.id for d in make_mesh(devices=list(reversed(devs))).devices.flat]
    assert ids == sorted(d.id for d in devs)
    assert ids == [d.id for d in make_mesh(devices=devs).devices.flat]


def test_sort_devices_knob():
    with knobs.scoped_env(SORT_DEVICES="4"):
        assert make_mesh().devices.size == 4
    with knobs.scoped_env(SORT_DEVICES="auto"):
        assert make_mesh().devices.size == len(jax.devices())
    with knobs.scoped_env(SORT_DEVICES=None):
        assert make_mesh().devices.size == len(jax.devices())
    with knobs.scoped_env(SORT_DEVICES=str(len(jax.devices()) + 1)):
        with pytest.raises(ValueError, match="requested"):
            make_mesh()
    for bad in ("0", "-1", "garbage"):
        with knobs.scoped_env(SORT_DEVICES=bad):
            with pytest.raises(ValueError, match="SORT_DEVICES"):
                knobs.get("SORT_DEVICES")


def test_scaleout_knob_validation():
    with knobs.scoped_env(SORT_NEGOTIATE="maybe"):
        with pytest.raises(ValueError, match="SORT_NEGOTIATE"):
            knobs.get("SORT_NEGOTIATE")
    with knobs.scoped_env(SORT_RESTAGE_RATIO="1.0"):
        with pytest.raises(ValueError, match="SORT_RESTAGE_RATIO"):
            knobs.get("SORT_RESTAGE_RATIO")
    with knobs.scoped_env(SORT_RESTAGE_RATIO="2.5"):
        assert knobs.get("SORT_RESTAGE_RATIO") == 2.5


# -------------------------------------------- report scale-out surface

def test_report_scaleout_pairs():
    from mpitest_tpu.report import scaleout_throughput

    metrics = {
        "radix_sort_mkeys_per_s_2e20_int32": {"value": 100.0},
        "radix_sort_mkeys_per_s_2e20_int32_8dev": {"value": 400.0,
                                                   "devices": 8},
        "sample_sort_mkeys_per_s_2e18_int32": {"value": 50.0},
        "sample_sort_mkeys_per_s_2e20_int32_8dev": {"value": 90.0,
                                                    "devices": 8},
    }
    pairs = {(p["algo"], p["dtype"]): p
             for p in scaleout_throughput(metrics)}
    assert pairs[("radix", "int32")]["speedup"] == 4.0
    # mismatched N: both rows surface, but no fabricated ratio
    assert "speedup" not in pairs[("sample", "int32")]


def test_report_baseline_devices_gate():
    from mpitest_tpu.report import flag_regressions

    current = {"metrics": {
        "radix_sort_mkeys_per_s_2e20_int32_8dev":
            {"value": 10.0, "devices": 1},
    }}
    baseline = [{"kind": "bench",
                 "metric": "radix_sort_mkeys_per_s_2e20_int32_8dev",
                 "value": 100.0, "devices": 8}]
    findings = flag_regressions(current, baseline, 0.9, host="h")
    assert findings[0]["status"] == "skipped"
    assert "devices mismatch" in findings[0]["reason"]
    # matching devices: compared normally (and here, regressing)
    current["metrics"][
        "radix_sort_mkeys_per_s_2e20_int32_8dev"]["devices"] = 8
    findings = flag_regressions(current, baseline, 0.9, host="h")
    assert findings[0]["status"] == "REGRESSION"
