"""Live-telemetry tests (ISSUE 10): the metrics registry + exposition
format, the span-close bridge, SORT_TRACE_SAMPLE root-coherent
sampling, trace-context propagation (solo / batched / retried / faulted
requests all carry one trace_id end to end), the flight recorder's
ring/dump contracts, report.py's live mode (--trace-id, error budget,
--prom), the telemetry HTTP endpoints, and the bench-history table.

In-process throughout (ServerCore + an ephemeral TelemetryServer); the
subprocess wire drills live in ``make telemetry-selftest``."""

from __future__ import annotations

import json
import threading
import urllib.request
from contextlib import contextmanager

import numpy as np
import pytest

from mpitest_tpu import report
from mpitest_tpu.utils import flight_recorder as fr
from mpitest_tpu.utils import knobs, metrics_live
from mpitest_tpu.utils.metrics_live import (LiveMetrics, SpanMetricsBridge,
                                            check_exposition,
                                            parse_prom_text)
from mpitest_tpu.utils.spans import SpanLog, trace_context


@contextmanager
def serve_core(**env):
    from mpitest_tpu.serve.server import ServerCore

    with knobs.scoped_env(**env):
        core = ServerCore()
        try:
            yield core
        finally:
            core.batcher.stop(timeout=10)


# ------------------------------------------------------ metrics registry

def test_counter_gauge_histogram_accuracy():
    m = LiveMetrics()
    c = m.counter("sort_serve_requests_total")
    c.inc(1, status="ok")
    c.inc(2, status="ok")
    c.inc(1, status="integrity")
    assert c.get(status="ok") == 3
    assert c.total() == 4
    with pytest.raises(ValueError):
        c.inc(-1)
    g = m.gauge("sort_serve_inflight")
    g.set(7)
    g.set(2)
    assert g.get() == 2
    h = m.histogram("sort_serve_request_latency_seconds")
    for v in (0.0004, 0.004, 0.04, 0.4, 400.0):
        h.observe(v)
    assert h.sample_count() == 5
    assert h.get() == pytest.approx(400.4444)


def test_unregistered_or_miskinded_metric_raises():
    m = LiveMetrics()
    with pytest.raises(KeyError):
        m.counter("sort_made_up_total")
    with pytest.raises(KeyError):
        m.gauge("sort_serve_requests_total")  # registered as a counter
    # the kind check holds on a WARM registry too: an existing counter
    # family must not be handed out as a gauge (set() would overwrite
    # the accumulated count)
    m.counter("sort_serve_requests_total").inc(1, status="ok")
    with pytest.raises(KeyError):
        m.gauge("sort_serve_requests_total")
    assert m.counter("sort_serve_requests_total").total() == 1


def test_exposition_roundtrip_and_escaping():
    m = LiveMetrics()
    m.counter("sort_faults_total").inc(1, site='we"ird\\site')
    m.histogram("sort_serve_batch_segments").observe(3)
    m.histogram("sort_serve_batch_segments").observe(100)  # > last bound
    text = m.render_prom()
    assert check_exposition(text) == []
    fams = parse_prom_text(text)
    assert fams["sort_faults_total"]["type"] == "counter"
    (_n, labels, v), = fams["sort_faults_total"]["samples"]
    assert labels == {"site": 'we"ird\\site'} and v == 1
    seg = {n: v for n, lbl, v in
           fams["sort_serve_batch_segments"]["samples"]
           if lbl.get("le") in ("4", "+Inf")}
    assert seg["sort_serve_batch_segments_bucket"] in (1, 2)
    # +Inf bucket == count == 2 (the 100 lands only there)
    cnt = [v for n, _l, v in fams["sort_serve_batch_segments"]["samples"]
           if n == "sort_serve_batch_segments_count"]
    assert cnt == [2]


def test_check_exposition_flags_unregistered_and_bad_grammar():
    bad = "# TYPE nope_total counter\nnope_total 3\n"
    assert any("not registered" in e for e in check_exposition(bad))
    assert check_exposition("sort_serve_inflight notanumber\n")
    with pytest.raises(ValueError):
        parse_prom_text("sort_serve_inflight oops\n")


def test_registry_vocabulary_is_well_formed():
    for name, (kind, help_text) in metrics_live.METRICS.items():
        assert kind in ("counter", "gauge", "histogram"), name
        assert help_text, name
    for name, buckets in metrics_live._HISTOGRAM_BUCKETS.items():
        assert metrics_live.METRICS[name][0] == "histogram"
        assert list(buckets) == sorted(buckets)


# ------------------------------------------------------------ the bridge

def test_span_bridge_maps_the_vocabulary():
    m = LiveMetrics()
    log = SpanLog()
    log.observers.append(SpanMetricsBridge(m))
    log.record("serve.request", 0.0, 0.02, status="ok", batched=True,
               n=100, queue_s=0.003)
    log.record("serve.request", 0.0, 0.5, status="integrity")
    log.record("serve.request", 0.0, 0.0, status="backpressure",
               reject="inflight")
    log.record("serve.batch", 0.0, 0.01, segments=4, keys=1200)
    log.record("serve.compile_cache", 0.0, 0.0, hit=False, compile_s=0.7)
    log.record("serve.compile_cache", 0.0, 0.0, hit=True)
    log.record("verify", 0.0, 0.0, ok=False)
    log.record("phase:verify", 0.0, 0.25)
    log.record("supervisor_retry", 0.0, 0.0, attempt=1)
    log.record("fault", 0.0, 0.0, site="exchange_drop")
    log.record("exchange_balance", 0.0, 0.0, recv_ratio=1.5,
               peer_ratio=2.0, negotiated_cap=256, worst_cap=2048,
               recv_bytes=[10, 20], send_bytes=[15, 15])
    log.record("sort.plan", 0.0, 0.0, algo="radix", regret=1.25,
               decisions={"cap": {"chosen": 256, "regret": 1.25},
                          "algo": {"chosen": "radix",
                                   "requested": "sample",
                                   "trigger": "skew_sniff",
                                   "regret": 0.0}})
    assert m.counter("sort_serve_requests_total").get(status="ok") == 1
    assert m.counter("sort_serve_requests_total").total() == 3
    # only the ok request is a latency sample
    assert m.histogram(
        "sort_serve_request_latency_seconds").sample_count() == 1
    assert m.histogram("sort_serve_queue_wait_seconds").sample_count() == 1
    assert m.counter("sort_serve_rejected_total").get(reason="inflight") == 1
    assert m.counter("sort_serve_batch_keys_total").total() == 1200
    assert m.counter("sort_serve_cache_misses_total").total() == 1
    assert m.counter("sort_serve_cache_hits_total").total() == 1
    assert m.counter("sort_serve_compile_seconds_total").total() == 0.7
    assert m.counter("sort_verify_failures_total").total() == 1
    assert m.counter("sort_verify_seconds_total").total() == 0.25
    assert m.counter("sort_retries_total").total() == 1
    assert m.counter("sort_faults_total").get(site="exchange_drop") == 1
    assert m.gauge("sort_exchange_peer_ratio").get() == 2.0
    assert m.gauge("sort_exchange_rank_recv_bytes").get(rank="1") == 20
    # plan provenance (ISSUE 12)
    assert m.counter("sort_plans_total").get(algo="radix") == 1
    assert m.gauge("sort_plan_regret").get() == 1.25
    assert m.gauge("sort_plan_cap_regret").get() == 1.25
    assert m.gauge("sort_plan_decision_regret").get(decision="cap") == 1.25
    assert m.counter("sort_plan_reroutes_total").get(
        trigger="skew_sniff") == 1


def test_bridge_errors_never_escape_the_span_path():
    log = SpanLog()

    def bomb(_s):
        raise RuntimeError("observer bug")

    log.observers.append(bomb)
    with log.span("sort"):
        pass
    assert log.spans[0].name == "sort"  # the path survived


# --------------------------------------------------------------- sampling

def test_trace_sample_drops_whole_subtrees_keeps_schema(tmp_path):
    stream = tmp_path / "trace.jsonl"
    with knobs.scoped_env(SORT_TRACE_SAMPLE="0.5"):
        log = SpanLog(stream_path=str(stream))
        for _ in range(6):
            with log.span("sort"):
                with log.span("phase:encode"):
                    log.event("verify", ok=True)
    rows = report.load_rows(str(stream))
    # every 2nd root kept -> exactly half the 18 spans streamed
    assert len(rows) == 9
    assert report.check_rows(rows) == []   # parent links all resolve
    # retention and export are unaffected by stream sampling
    assert len(log.spans) == 18


def test_trace_sample_holds_for_any_rate(tmp_path):
    """Error-diffusion keeps EXACTLY floor-accurate fractions at any
    rate — a keep-every-Nth quantization would silently keep 100% for
    every rate above 2/3."""
    for rate, total, kept in (("0.75", 8, 6), ("0.9", 10, 9),
                              ("0.25", 8, 2)):
        stream = tmp_path / f"t{rate}.jsonl"
        with knobs.scoped_env(SORT_TRACE_SAMPLE=rate):
            log = SpanLog(stream_path=str(stream))
            for _ in range(total):
                with log.span("sort"):
                    pass
        assert len(report.load_rows(str(stream))) == kept, rate


def test_trace_sample_one_keeps_everything(tmp_path):
    stream = tmp_path / "trace.jsonl"
    log = SpanLog(stream_path=str(stream))
    with log.span("sort"):
        log.event("verify", ok=True)
    assert len(report.load_rows(str(stream))) == 2


# ---------------------------------------------------------- trace context

def test_trace_context_nesting_and_precedence():
    log = SpanLog()
    with trace_context(batch_id="b1"):
        with trace_context(trace_id="t1"):
            log.record("serve.request", 0.0, 0.1, n=1)
            # explicit attrs beat context attrs
            log.record("serve.request", 0.0, 0.1, trace_id="override")
        log.record("serve.batch", 0.0, 0.1)
    log.record("verify", 0.0, 0.0)
    a = [s.attrs for s in log.spans]
    assert a[0]["trace_id"] == "t1" and a[0]["batch_id"] == "b1"
    assert a[1]["trace_id"] == "override"
    assert a[2] == {"batch_id": "b1"}
    assert "batch_id" not in a[3]


def test_trace_context_is_thread_local():
    log = SpanLog()
    seen = {}

    def other():
        log.record("verify", 0.0, 0.0)
        seen["attrs"] = log.spans[-1].attrs

    with trace_context(trace_id="main-only"):
        t = threading.Thread(target=other)
        t.start()
        t.join()
    assert "trace_id" not in seen["attrs"]


def test_worker_records_inherit_the_open_spans_context():
    """Pipeline worker threads (ingest/egress stages) report via
    SpanLog.record under the driver's innermost open span — they must
    inherit THAT span's trace context, or large streamed-ingest
    requests would lose their ingest stages from the --trace-id view."""
    log = SpanLog()
    done = threading.Event()
    go = threading.Event()

    def worker():
        go.wait(5)
        log.record("ingest.parse", 0.0, 0.01, bytes=4)
        done.set()

    t = threading.Thread(target=worker)
    t.start()
    with trace_context(trace_id="big-req"):
        with log.span("sort"):
            go.set()
            assert done.wait(5)
    t.join()
    parse = [s for s in log.spans if s.name == "ingest.parse"]
    assert parse[0].attrs["trace_id"] == "big-req"
    # ...and outside any open span, no inheritance happens
    log.record("ingest.parse", 0.0, 0.01)
    assert "trace_id" not in log.spans[-1].attrs


# -------------------------------------------------------- flight recorder

def test_flight_ring_bound_and_dump_sanitizes_parents(tmp_path):
    with knobs.scoped_env(SORT_FLIGHT_RECORDER_SIZE="8",
                          SORT_FLIGHT_RECORDER_DIR=str(tmp_path)):
        fr.reset()
        try:
            log = SpanLog()
            with log.span("sort"):              # root: evicted later
                for _ in range(12):             # children flood the ring
                    log.event("verify", ok=True)
            rec = fr.get()
            assert rec.capacity == 8 and len(rec.ring) == 8
            # the ring holds late children + the root (flushed LAST);
            # early children's parent links must sanitize away
            path = rec.dump("unit_test")
            assert path is not None
            rows = report.load_rows(path)
            assert report.check_rows(rows) == []
            assert sum(1 for r in rows if r.get("kind") == "span") == 8
            # rate limit: same reason immediately again -> no dump
            assert rec.dump("unit_test", rate_limit=True) is None
            # a DIFFERENT reason dumps fine
            assert rec.dump("other_reason", rate_limit=True) is not None
        finally:
            fr.reset()


def test_flight_recorder_disabled_at_size_zero(tmp_path):
    with knobs.scoped_env(SORT_FLIGHT_RECORDER_SIZE="0",
                          SORT_FLIGHT_RECORDER_DIR=str(tmp_path)):
        fr.reset()
        try:
            log = SpanLog()
            with log.span("sort"):
                pass
            rec = fr.get()
            assert not rec.enabled
            assert rec.dump("nope") is None
        finally:
            fr.reset()


def test_typed_error_dumps_flight_artifact(tmp_path, rng, mesh8):
    """The acceptance path: a fault-injected typed error leaves an
    artifact report.py --check accepts (ISSUE 10)."""
    from mpitest_tpu.models import api
    from mpitest_tpu.models.supervisor import SortIntegrityError

    with knobs.scoped_env(SORT_FLIGHT_RECORDER_DIR=str(tmp_path),
                          SORT_FAULTS="result_swap:inf",
                          SORT_FALLBACK="0", SORT_MAX_RETRIES="0"):
        fr.reset()
        try:
            x = rng.integers(-2**31, 2**31 - 1, size=4096, dtype=np.int32)
            with pytest.raises(SortIntegrityError):
                api.sort(x, algorithm="radix", mesh=mesh8)
            dumps = sorted(tmp_path.glob("flight-*.jsonl"))
            # two artifacts: the fault-site trigger, then the typed
            # error itself (the later one carries the whole story)
            assert len(dumps) == 2, dumps
            for d in dumps:
                assert report.main(["--check", str(d)]) == 0
            names = {r.get("name")
                     for r in report.load_rows(str(dumps[-1]))}
            assert "fault" in names and "verify" in names
        finally:
            fr.reset()


# ------------------------------------------- trace propagation (serving)

def test_batched_requests_share_batch_id_keep_trace_ids(rng):
    with serve_core(SORT_SERVE_BATCH_WINDOW_MS="60") as core:
        arrs = [rng.integers(-2**31, 2**31 - 1, size=300, dtype=np.int32)
                for _ in range(3)]
        res: dict = {}

        def send(i):
            res[i] = core.execute(arrs[i], trace_id=f"tt{i}")

        ts = [threading.Thread(target=send, args=(i,)) for i in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for i in range(3):
            st, _out, attrs = res[i]
            assert st == "ok"
            assert attrs["trace_id"] == f"tt{i}"
            assert attrs["queue_s"] >= 0
        bids = {res[i][2]["batch_id"] for i in range(3)}
        assert len(bids) == 1
        batch = [s for s in core.tracer.spans.spans
                 if s.name == "serve.batch"]
        assert sorted(batch[-1].attrs["trace_ids"]) == ["tt0", "tt1", "tt2"]
        assert batch[-1].attrs["batch_id"] == bids.pop()


def test_solo_request_stamps_every_sort_span(rng):
    with serve_core(SORT_SERVE_BATCH_KEYS="128") as core:  # force solo
        a = rng.integers(-2**31, 2**31 - 1, size=3000, dtype=np.int32)
        st, out, attrs = core.execute(a, trace_id="solo-t")
        assert st == "ok" and np.array_equal(out, np.sort(a))
        assert attrs["batched"] is False
        stamped = {s.name for s in core.tracer.spans.spans
                   if s.attrs.get("trace_id") == "solo-t"}
        # the umbrella, its phases, the verifier AND the reply span all
        # carry the request's identity
        assert {"serve.request", "sort", "verify"} <= stamped
        assert any(n.startswith("phase:") for n in stamped)


def test_retried_and_faulted_requests_keep_their_trace_id(rng, mesh8):
    with serve_core(SORT_SERVE_ALLOW_FAULTS="1",
                    SORT_MAX_RETRIES="2", SORT_FALLBACK="0") as core:
        a = rng.integers(-2**31, 2**31 - 1, size=2048, dtype=np.int32)
        st, out, _ = core.execute(a, faults_spec="dispatch_error:1",
                                  trace_id="retry-t")
        assert st == "ok" and np.array_equal(out, np.sort(a))
        retries = [s for s in core.tracer.spans.spans
                   if s.name == "supervisor_retry"]
        assert retries and retries[-1].attrs["trace_id"] == "retry-t"

    with serve_core(SORT_SERVE_ALLOW_FAULTS="1",
                    SORT_MAX_RETRIES="0", SORT_FALLBACK="0") as core:
        a = rng.integers(-2**31, 2**31 - 1, size=2048, dtype=np.int32)
        st, _detail, attrs = core.execute(a, faults_spec="result_swap:inf",
                                          trace_id="bad-t")
        assert st == "integrity" and attrs["trace_id"] == "bad-t"
        faulted = [s for s in core.tracer.spans.spans
                   if s.name == "fault"
                   and s.attrs.get("trace_id") == "bad-t"]
        assert faulted, "fault events lost the request identity"


# ------------------------------------------------------- report live mode

_row_ids = iter(range(10_000))


def _span_row(name, t0, dt, **attrs):
    return {"kind": "span", "v": "span.v1", "name": name,
            "id": next(_row_ids), "parent": None, "t0": t0, "dt": dt,
            "pid": 1, "attrs": attrs}


def test_trace_view_reconstructs_without_leaking_batchmates():
    rows = [
        _span_row("serve.request", 0.0, 0.1, trace_id="A", status="ok",
                  n=10, dtype="int32", queue_s=0.01, batched=True,
                  bucket=1024, batch_id="b1"),
        _span_row("serve.request", 0.0, 0.2, trace_id="B", status="ok",
                  n=20, batch_id="b1"),
        _span_row("serve.batch", 0.05, 0.04, batch_id="b1",
                  trace_ids=["A", "B"], segments=2, keys=30),
        _span_row("serve.compile_cache", 0.06, 0.0, batch_id="b1",
                  hit=True),
        _span_row("sort", 0.0, 0.5, trace_id="Z"),   # unrelated request
    ]
    view = report.trace_view(rows, "A")
    assert view is not None
    assert "serve.batch" in view and "serve.compile_cache" in view
    assert "+1 batchmate(s)" in view and "queue_wait=10.000ms" in view
    assert "n=20" not in view            # batchmate B's request excluded
    assert report.trace_view(rows, "nope") is None


def test_serve_slo_error_budget_and_render():
    serve = {"requests": [
        {"dt": 0.01, "status": "ok", "batched": True, "n": 5},
        {"dt": 0.01, "status": "ok", "batched": False, "n": 5},
        {"dt": 0.5, "status": "integrity", "batched": False, "n": 5},
    ], "batches": 1, "batch_segments": 2, "batch_keys": 10,
        "cache_hits": 1, "cache_misses": 0, "compile_s": 0.0}
    slo = report.serve_slo(serve, slo_target=99.0)
    assert slo["error_rate_pct"] == pytest.approx(33.3333, abs=1e-3)
    assert slo["budget_burn"] == pytest.approx(33.33, abs=0.01)
    agg = {"phases": {}, "collectives": {}, "metrics": {}, "spans": {},
           "ingest": {}, "robustness": {}, "scaleout": {},
           "serve": serve, "tooling": None, "encode_engines": [],
           "ingest_overlap": None, "egress_overlap": None}
    text = report.render(agg, slo_target=99.0)
    assert "error budget (99.0% target)" in text and "burn" in text


def test_report_prom_snapshot_rendering(tmp_path):
    m = LiveMetrics()
    m.counter("sort_serve_requests_total").inc(99, status="ok")
    m.counter("sort_serve_requests_total").inc(1, status="internal")
    f = tmp_path / "scrape.prom"
    f.write_text(m.render_prom())
    out = report.render_prom_snapshot(str(f), f.read_text())
    assert "requests internal=1, ok=99" in out
    assert "error budget" in out and "burn 10.0x" in out
    assert report.main(["--prom", str(f)]) == 0


# ----------------------------------------------------- telemetry endpoints

def test_telemetry_http_endpoints(rng):
    from mpitest_tpu.serve.telemetry import TelemetryServer

    with serve_core(SORT_SERVE_BATCH_WINDOW_MS="0") as core:
        tel = TelemetryServer(core, "127.0.0.1", 0)
        tel.start()
        try:
            a = rng.integers(-100, 100, size=256, dtype=np.int32)
            assert core.execute(a, trace_id="ep-t")[0] == "ok"
            base = f"http://127.0.0.1:{tel.bound_port}"

            def get(path):
                with urllib.request.urlopen(base + path, timeout=10) as r:
                    return r.status, r.read()

            st, body = get("/metrics")
            assert st == 200
            assert check_exposition(body.decode()) == []
            fams = parse_prom_text(body.decode())
            assert fams["sort_serve_requests_total"]["samples"]
            st, body = get("/healthz")
            assert st == 200 and json.loads(body)["ok"] is True
            st, body = get("/varz")
            vz = json.loads(body)
            assert st == 200 and "admission" in vz and "mesh" in vz
            # rolling decision snapshot (ISSUE 12), fed from the ring
            plans = vz["plans"]
            assert plans["plans"] >= 1
            assert "cap" in plans["decisions"]
            assert plans["last"]["algo"] is not None
            st, body = get("/flightrecorder")
            assert st == 200
            rows = [json.loads(ln) for ln in body.decode().splitlines()
                    if ln]
            assert any(r.get("name") == "serve.request" for r in rows)
            # draining flips healthz to 503
            core.start_drain()
            try:
                urllib.request.urlopen(base + "/healthz", timeout=10)
                raise AssertionError("expected 503 while draining")
            except urllib.error.HTTPError as e:
                assert e.code == 503
        finally:
            tel.shutdown()
            tel.server_close()


# ---------------------------------------------------------- bench history

def _bench_envelope(tail_lines):
    return json.dumps({"n": 1, "cmd": "bench", "rc": 0,
                       "tail": "\n".join(tail_lines), "parsed": {}})


def test_bench_history_table_and_regression_flags(tmp_path):
    from tools import bench_history as bh

    side1 = json.dumps({"ts": 1, "config": {}, "metrics": {
        "sort_mkeys_per_s": {"value": 100.0},
        "sort_incl_ingest_mkeys_per_s": {"value": 50.0}}})
    row1 = json.dumps({"metric": "radix_sort_mkeys_per_s_2e20_int32",
                       "value": 100.0})
    (tmp_path / "BENCH_r01.json").write_text(
        _bench_envelope(["noise", side1, row1]))
    side2 = json.dumps({"ts": 2, "config": {}, "metrics": {
        "sort_mkeys_per_s": {"value": 60.0},     # regressed
        "sort_incl_ingest_mkeys_per_s": {"value": 55.0}}})
    serve_row = json.dumps({"metric": "serve_small_mix_mkeys_per_s",
                            "value": 0.5, "p99_ms": 20.0})
    (tmp_path / "BENCH_r02.json").write_text(
        _bench_envelope([side2, serve_row]))
    runs = bh.find_runs(tmp_path)
    assert [r[0] for r in runs] == [1, 2]
    table, flags = bh.build_table(runs)
    assert "| r01 | 100 |" in table
    assert "⚠" in table and flags and "sort" in flags[0]
    # derived ingest ratio appears for both rounds
    assert "0.5" in table
    assert bh.main(["--dir", str(tmp_path)]) == 0
    assert bh.main(["--dir", str(tmp_path), "--strict"]) == 2
