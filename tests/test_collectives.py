"""Per-primitive isolation tests for the XLA comm layer.

The native shim has ``native/comm_selftest.c`` ("each primitive checked
in isolation so a shim bug cannot hide behind an algorithm bug" —
SURVEY.md §4); this file is its twin for the Python/XLA side
(``mpitest_tpu/parallel/collectives.py``), on the virtual 8-device mesh:
closed-form checks for rank/all_gather/psum/exscan, and randomized
ragged configurations (zero-length segments, overflow past the cap)
for ``ragged_all_to_all`` against a numpy reference — both pack
implementations.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from mpitest_tpu.parallel import collectives as coll
from mpitest_tpu.parallel.mesh import AXIS
from mpitest_tpu import compat

P_ = 8  # mesh8 fixture (conftest.py) provides the 8-device virtual mesh


def spmd(mesh, f, in_specs, out_specs, check_vma=True):
    # pallas_call internals mix varying/unvarying operands in ways the
    # vma checker rejects (same exemption as models/api.py's compiles)
    return jax.jit(compat.shard_map(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=check_vma))


def test_rank_allgather_psum_pmax(mesh8):
    def f(x):
        r = coll.rank()
        gathered = coll.all_gather(x)          # [P, n]
        total = coll.psum(x)
        biggest = coll.pmax(x)
        return r[None], gathered[None], total, biggest

    x = np.arange(P_ * 4, dtype=np.int32)
    ranks, gathered, total, biggest = spmd(
        mesh8, f, (P(AXIS),), (P(AXIS), P(AXIS), P(), P()),
    )(x)
    np.testing.assert_array_equal(np.asarray(ranks), np.arange(P_))
    # every rank gathered the same full [P, 4] matrix
    g = np.asarray(gathered).reshape(P_, P_, 4)
    for r in range(P_):
        np.testing.assert_array_equal(g[r], x.reshape(P_, 4))
    np.testing.assert_array_equal(np.asarray(total),
                                  x.reshape(P_, 4).sum(axis=0))
    np.testing.assert_array_equal(np.asarray(biggest),
                                  x.reshape(P_, 4).max(axis=0))


def test_exclusive_cumsum():
    x = np.array([[3, 1], [4, 1], [5, 9]], np.int32)
    got = np.asarray(coll.exclusive_cumsum(jnp.asarray(x), axis=0))
    np.testing.assert_array_equal(got, np.array([[0, 0], [3, 1], [7, 2]]))


def test_exscan_counts(mesh8):
    """The MPI_Exscan + Allreduce census rows, in one primitive: H is
    every rank's histogram, tot the global sum, rank_base the exclusive
    prefix over ranks (rank 0 = identity, defined — unlike MPI)."""
    B = 5
    rng = np.random.default_rng(0)
    hists = rng.integers(0, 100, size=(P_, B)).astype(np.int32)

    def f(h):
        H, tot, rank_base = coll.exscan_counts(h.reshape(-1))
        return H[None], tot[None], rank_base[None]

    H, tot, rank_base = spmd(
        mesh8, f, (P(AXIS),), (P(AXIS), P(AXIS), P(AXIS)),
    )(hists.reshape(-1))
    H = np.asarray(H).reshape(P_, P_, B)
    tot = np.asarray(tot).reshape(P_, B)
    rank_base = np.asarray(rank_base).reshape(P_, P_, B)
    want_base = np.cumsum(hists, axis=0) - hists
    for r in range(P_):  # replicated results identical on every rank
        np.testing.assert_array_equal(H[r], hists)
        np.testing.assert_array_equal(rank_base[r], want_base)
        np.testing.assert_array_equal(tot[r], hists.sum(axis=0))


def _ragged_reference(data, starts, cnts, cap):
    """numpy model: recv[d][s] = first min(cnt, cap) elements of the
    segment rank s sent to rank d."""
    recv = np.zeros((P_, P_, cap), np.uint32)
    rcnt = np.zeros((P_, P_), np.int32)
    for d in range(P_):
        for s in range(P_):
            c = min(int(cnts[s, d]), cap)
            seg = data[s, starts[s, d]:starts[s, d] + c]
            recv[d, s, :c] = seg
            rcnt[d, s] = c
    return recv, rcnt


@pytest.mark.parametrize("pack", ["xla", "pallas_interpret"])
@pytest.mark.parametrize("seed,cap_mode", [(0, "fits"), (1, "fits"),
                                           (2, "overflow"), (3, "zeros")])
def test_ragged_all_to_all_random(pack, seed, cap_mode, mesh8):
    """Randomized Alltoallv configurations vs the numpy reference:
    ragged per-peer counts (including all-zero rows), and caps smaller
    than the largest segment (overflow must clip AND report the exact
    global max so the caller can retry)."""
    from mpitest_tpu.ops.pallas_kernels import CHUNK

    rng = np.random.default_rng(seed)
    n = 4 * CHUNK  # per-shard elements; CHUNK-aligned for the Pallas pack
    hi = 0 if cap_mode == "zeros" else 2 * n // P_
    cnts = rng.integers(0, max(hi, 1), size=(P_, P_)).astype(np.int32)
    cnts = np.minimum(cnts, n // P_)  # total per rank must fit its shard
    starts = (np.cumsum(cnts, axis=1) - cnts).astype(np.int32)
    data = rng.integers(0, 2**32, size=(P_, n), dtype=np.uint32)
    # the Pallas pack requires CHUNK-multiple caps (api.py rounds caps
    # accordingly); the XLA spread takes any cap
    cap = CHUNK if (cap_mode != "overflow" or pack.startswith("pallas")) \
        else CHUNK // 8
    if cap_mode == "overflow":
        cnts[0, :] = 0
        cnts[0, 3] = min(n, cap * 3)  # one oversized segment, total <= n
        starts = (np.cumsum(cnts, axis=1) - cnts).astype(np.int32)

    def f(d, st, ct):
        recv, rcnt, mx = coll.ragged_all_to_all(
            (d,), st.reshape(-1), ct.reshape(-1), cap, P_, pack=pack,
        )
        return recv[0][None], rcnt[None], mx

    recv, rcnt, mx = spmd(
        mesh8, f, (P(AXIS), P(AXIS), P(AXIS)), (P(AXIS), P(AXIS), P()),
        check_vma=(pack == "xla"),
    )(data.reshape(-1), starts, cnts)
    recv = np.asarray(recv).reshape(P_, P_, cap)
    rcnt = np.asarray(rcnt).reshape(P_, P_)
    want_recv, want_rcnt = _ragged_reference(data, starts, cnts, cap)
    np.testing.assert_array_equal(rcnt, want_rcnt)
    assert int(mx) == int(cnts.max())  # exact retry cap, globally reduced
    for d in range(P_):
        for s in range(P_):
            np.testing.assert_array_equal(
                recv[d, s, :rcnt[d, s]], want_recv[d, s, :rcnt[d, s]],
                err_msg=f"dst {d} src {s}",
            )
