"""AOT-compile the multi-chip programs for a REAL v5e-8 TPU topology.

The CPU-mesh tests prove the SPMD logic; this proves the actual TPU
compiler accepts the 8-chip programs — XLA collectives over the ICI
mesh, the Pallas DMA exchange pack, and the Pallas bitonic engine under
``shard_map`` — using an *abstract* topology descriptor, no TPU chips
required (``jax.experimental.topologies``; libtpu does the compile).
This is the strongest multi-chip validation available on a single-chip
image, complementing ``__graft_entry__.dryrun_multichip`` (which
executes on the virtual CPU mesh).
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mpitest_tpu.models import radix_sort, sample_sort
from mpitest_tpu.parallel.mesh import AXIS
from mpitest_tpu import compat
# The bounded subprocess probe (PR 5's GIL-hang fix) now lives in
# mpitest_tpu/utils/topology_probe.py, shared with the sort server's
# executor cache (ISSUE 8): get_topology_desc blocks forever HOLDING
# THE GIL on a tunnel-less image, so only a killable child process can
# bound it.  The verdict is cached per process.
from mpitest_tpu.utils.topology_probe import probe_tpu_compiler


def _topology_or_skip(topology_name: str, num_slices: int | None = None):
    """``topologies.get_topology_desc`` behind the bounded connect
    probe: once the probe proves the tunnel answers, the in-process
    fetch is safe (same endpoint, already-warm metadata)."""
    reason = probe_tpu_compiler()
    if reason:
        pytest.skip(reason)
    try:
        from jax.experimental import topologies

        kw = {"num_slices": num_slices} if num_slices else {}
        return topologies.get_topology_desc(
            platform="tpu", topology_name=topology_name, **kw)
    except Exception as e:  # noqa: BLE001 — no libtpu / unsupported API
        pytest.skip(f"TPU topology AOT unavailable: {type(e).__name__}: {e}")


@pytest.fixture(scope="module")
def v5e8_mesh():
    topo = _topology_or_skip("v5e:2x4")
    return Mesh(np.array(topo.devices).reshape(8), (AXIS,))


def _sharded_input(mesh, n_per_chip):
    return jax.ShapeDtypeStruct(
        (8 * n_per_chip,), jnp.uint32,
        sharding=NamedSharding(mesh, P(AXIS)),
    )


def test_aot_radix_v5e8(v5e8_mesh):
    """Full 2-pass 16-bit-digit radix step over 8 chips compiles."""
    n, cap = 1 << 14, 1 << 12

    def step(words):
        out, mc = radix_sort.radix_sort_spmd(words, 1, 16, 8, cap, 2)
        return out[0], mc

    fn = compat.shard_map(step, mesh=v5e8_mesh, in_specs=((P(AXIS),),),
                       out_specs=(P(AXIS), P()))
    compiled = jax.jit(fn).lower((_sharded_input(v5e8_mesh, n),)).compile()
    assert compiled is not None


def test_aot_sample_pallas_v5e8(v5e8_mesh):
    """Sample sort with BOTH Pallas paths — the DMA exchange pack and the
    bitonic per-shard engine (real Mosaic kernels, not interpret mode) —
    compiles over 8 chips."""
    n, cap = 1 << 14, 1 << 12

    def step(words):
        out, cnt, mc = sample_sort.sample_sort_spmd(
            words, 1, 8, cap, 15, pack="pallas", engine="bitonic")
        return out[0], cnt[None], mc

    fn = compat.shard_map(step, mesh=v5e8_mesh, in_specs=((P(AXIS),),),
                       out_specs=(P(AXIS), P(AXIS), P()), check_vma=False)
    compiled = jax.jit(fn).lower((_sharded_input(v5e8_mesh, n),)).compile()
    assert compiled is not None


def test_aot_pair_engine_v5e8(v5e8_mesh):
    """The 64-bit PAIR engine (round 4) — pair block sort / cross /
    merge kernels + the in-VMEM run-fix kernel + the on-device residual
    cond — compiles as REAL Mosaic kernels under shard_map over 8
    chips: the distributed sample path's 2-word per-shard sort.  CI
    otherwise only interprets these kernels; this is the lowering
    gate (`make chip-test` is the numerics gate)."""
    n, cap = 1 << 14, 1 << 13

    def step(words):
        out, cnt, mc = sample_sort.sample_sort_spmd(
            words, 2, 8, cap, 15, pack="pallas", engine="bitonic")
        return out[0], out[1], cnt[None], mc

    fn = compat.shard_map(step, mesh=v5e8_mesh, in_specs=((P(AXIS), P(AXIS)),),
                       out_specs=(P(AXIS), P(AXIS), P(AXIS), P()),
                       check_vma=False)
    words = (_sharded_input(v5e8_mesh, n), _sharded_input(v5e8_mesh, n))
    compiled = jax.jit(fn).lower(words).compile()
    assert compiled is not None


def test_aot_pair_local_fused_v5e8(v5e8_mesh):
    """The fused single-device adaptive 64-bit program (encode + range +
    sniff + lax.cond tree over 1-word engine / lax / pair engine,
    models/api.py::_compile_pair_fused) lowers through the real TPU
    compiler for one chip of the topology — every cond branch compiles,
    including the constant-word 1-word-engine branches."""
    from mpitest_tpu.models.api import _compile_pair_fused

    dev = v5e8_mesh.devices.flat[0]
    x = jax.ShapeDtypeStruct(
        (1 << 14,), jnp.int64,
        sharding=NamedSharding(Mesh(np.array([dev]), (AXIS,)), P()),
    )
    with compat.enable_x64(True):
        fn = _compile_pair_fused("int64", "bitonic")
        assert fn.lower(x).compile() is not None


def test_aot_radix_v5e16_two_slices():
    """The BASELINE row-5 hardware config (v5e-16 = two 2x4 slices):
    the radix program compiles over the hybrid DCN+ICI 16-chip mesh —
    the 1-D logical axis keeps the algorithm topology-agnostic
    (SURVEY.md §7.3 'Multi-host')."""
    topo = _topology_or_skip("v5e:2x4", num_slices=2)
    mesh = Mesh(np.array(topo.devices).reshape(-1), (AXIS,))
    n_chips, n, cap = 16, 1 << 13, 1 << 11

    def step(words):
        out, mc = radix_sort.radix_sort_spmd(words, 1, 16, n_chips, cap, 2)
        return out[0], mc

    fn = compat.shard_map(step, mesh=mesh, in_specs=((P(AXIS),),),
                       out_specs=(P(AXIS), P()))
    x = jax.ShapeDtypeStruct((n_chips * n,), jnp.uint32,
                             sharding=NamedSharding(mesh, P(AXIS)))
    assert jax.jit(fn).lower((x,)).compile() is not None
