"""Interpret-mode tests for the Pallas segment-pack kernel (no TPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from mpitest_tpu.ops.pallas_kernels import CHUNK, segment_pack


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_segment_pack_interpret(seed, rng):
    P, cap = 4, 4 * CHUNK
    n = 1000 + seed * 37
    data = rng.integers(0, 2**32, n, dtype=np.uint32)
    cuts = np.sort(rng.integers(0, n + 1, P - 1))
    starts = np.concatenate([[0], cuts]).astype(np.int32)
    ends = np.concatenate([cuts, [n]]).astype(np.int32)
    cnts = ends - starts
    out = np.asarray(
        segment_pack(jnp.asarray(data), jnp.asarray(starts), jnp.asarray(cnts),
                     cap, P, fill=7, interpret=True)
    )
    # valid lanes must match exactly; beyond-count lanes are don't-care
    for p in range(P):
        c = min(int(cnts[p]), cap)
        np.testing.assert_array_equal(out[p, :c], data[starts[p]:starts[p] + c])
    # fully-beyond-count chunks carry the fill word
    for p in range(P):
        first_fill_chunk = ((int(cnts[p]) + CHUNK - 1) // CHUNK) * CHUNK
        if first_fill_chunk < cap:
            assert np.all(out[p, first_fill_chunk:] == 7)


@pytest.mark.parametrize("algo", ["radix", "sample"])
def test_models_with_pallas_pack_interpret(algo, mesh4, rng):
    """Full sort programs with the Pallas exchange pack (interpret mode on
    the CPU mesh) — exercises the wiring api → models → collectives →
    segment_pack end to end."""
    from mpitest_tpu.models.api import sort

    x = rng.integers(-(2**31), 2**31 - 1, size=3000, dtype=np.int32)
    got = sort(x, algorithm=algo, mesh=mesh4, pack="pallas_interpret")
    np.testing.assert_array_equal(got, np.sort(x))


def test_ragged_gather_probe_correctness():
    """The linear-work-movement experiment kernel (BASELINE.md round-3
    section, bench/ragged_gather_probe.py) stays correct: every sweep
    configuration asserts its dual position-weighted checksum against
    the numpy concatenation — run here in interpret mode on CPU."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    r = subprocess.run(
        [sys.executable, str(repo / "bench" / "ragged_gather_probe.py"),
         "--log2n", "14", "--interpret", "--platform", "cpu"],
        capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "MISMATCH" not in r.stdout + r.stderr


def test_segment_pack_empty_segments(rng):
    P, cap = 8, 2 * CHUNK
    data = rng.integers(0, 2**32, 300, dtype=np.uint32)
    # everything in one middle segment
    starts = np.array([0, 0, 0, 0, 300, 300, 300, 300], np.int32)
    cnts = np.array([0, 0, 0, 300, 0, 0, 0, 0], np.int32)
    out = np.asarray(
        segment_pack(jnp.asarray(data), jnp.asarray(starts), jnp.asarray(cnts),
                     cap, P, fill=0, interpret=True)
    )
    np.testing.assert_array_equal(out[3, :300], data)
    assert np.all(out[0] == 0) and np.all(out[7] == 0)
