"""Self-tuning planner (ISSUE 14): policy scoring units, the learned
cap-margin quantiles, serve-tuner hysteresis (an oscillating mix never
flips the window twice in a row), shadow-mode byte identity, the
verify-passthrough rung (hit AND miss), ladder recovery when a planner
choice faults, the flight-recorder snapshot API, and knob validation.

Uses the session-wide virtual 8-device CPU mesh from conftest.py.
"""

from __future__ import annotations

import pathlib
import sys
import threading

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from mpitest_tpu.models import plan as plan_mod  # noqa: E402
from mpitest_tpu.models import planner as planner_mod  # noqa: E402
from mpitest_tpu.models.api import sort  # noqa: E402
from mpitest_tpu.utils import flight_recorder, knobs  # noqa: E402
from mpitest_tpu.utils.trace import Tracer  # noqa: E402


def run_sort(x, algo="radix", mesh=None, **env):
    tracer = Tracer()
    with knobs.scoped_env(**env):
        out = sort(x, algorithm=algo, mesh=mesh, tracer=tracer)
    return out, tracer


def near_sorted(n: int, runs: int = 32, seed: int = 0) -> np.ndarray:
    """Overlapping ascending runs: ~runs/1024 of the strided profile's
    adjacent sample pairs decrease — near-sorted, never fully sorted."""
    rng = np.random.default_rng(seed)
    span = (1 << 31) // runs
    base = np.repeat(np.arange(runs, dtype=np.int64) * span, n // runs)
    # sort PER RUN (axis=1): run i ascends over [i*span, (i+2)*span) —
    # a global sort here would make the whole array sorted
    off = np.sort(rng.integers(0, 2 * span, size=(runs, n // runs)),
                  axis=1).reshape(-1)
    return (base + off - (1 << 30)).astype(np.int32)


# ------------------------------------------------- policy scoring units

def test_choose_sorted_profile_is_passthrough():
    c = planner_mod.choose({"sortedness": 1.0, "dup_ratio": 0.0},
                           "radix", verify_on=True)
    assert c.policy == "verify_passthrough"
    assert c.trigger == "sorted"
    assert c.algo is None  # a miss falls through to the requested algo


def test_choose_sorted_without_verifier_never_skips_the_sort():
    # the verifier is the passthrough's proof; without it the profile
    # is a guess, and the scorer must fall through (here: merge_sample)
    c = planner_mod.choose({"sortedness": 1.0, "dup_ratio": 0.0},
                           "radix", verify_on=False)
    assert c.policy != "verify_passthrough"


def test_choose_dup_heavy_beats_near_sorted():
    # a dup-heavy near-sorted input would degenerate sample splitters:
    # the duplicate check must outrank the near-sorted one
    c = planner_mod.choose({"sortedness": 0.95, "dup_ratio": 0.6},
                           "sample", verify_on=True)
    assert c.policy == "radix_narrow"
    assert c.algo == "radix"


def test_choose_near_sorted_is_merge_sample():
    c = planner_mod.choose({"sortedness": 0.95, "dup_ratio": 0.01},
                           "radix", verify_on=True)
    assert c.policy == "merge_sample"
    assert c.algo == "sample"


def test_choose_uniform_and_empty_profiles_are_static():
    assert planner_mod.choose({"sortedness": 0.5, "dup_ratio": 0.0},
                              "radix", verify_on=True).policy == "static"
    c = planner_mod.choose({}, "radix", verify_on=True)
    assert c.policy == "static"
    assert c.trigger == "no_profile"


def test_policy_registry_lookup():
    assert planner_mod.policy("static")
    assert all(doc for doc in planner_mod.PLANNER_POLICIES.values())
    with pytest.raises(KeyError):
        planner_mod.policy("warp_speed")


# --------------------------------------------------- learned cap margin

class _FakeSpan:
    def __init__(self, seq: int, name: str, attrs: dict) -> None:
        self.name = name
        self._d = {"pid": 1, "id": seq, "parent": None, "name": name,
                   "attrs": attrs}

    def to_dict(self) -> dict:
        return dict(self._d)


def _estimate_plan_span(seq: int, pred_need: float,
                        actual_need: float) -> _FakeSpan:
    return _FakeSpan(seq, "sort.plan", {
        "decisions": {"cap": {"trigger": "estimate",
                              "predicted": {"need": pred_need},
                              "actual": {"need": actual_need}}}})


@pytest.fixture
def ring(monkeypatch):
    rec = flight_recorder.FlightRecorder(256, "/tmp")
    monkeypatch.setattr(flight_recorder, "get", lambda: rec)
    return rec


def test_learned_margin_needs_enough_samples(ring):
    for i in range(planner_mod.MARGIN_MIN_SAMPLES - 1):
        ring.add(_estimate_plan_span(i, 100, 104))
    m, ev = planner_mod.learned_margin(1.25)
    assert m == 1.25
    assert ev["margin_learned"] is False


def test_learned_margin_sizes_from_observed_quantiles(ring):
    # 20 estimate decisions with error ratios 1.00..1.09: the learned
    # margin lands near q95*pad — far below the hand-set 1.25
    for i in range(20):
        ring.add(_estimate_plan_span(i, 1000, 1000 + 5 * (i % 10)))
    m, ev = planner_mod.learned_margin(1.25)
    assert ev["margin_learned"] is True
    assert ev["margin_samples"] == 20
    assert planner_mod.MARGIN_MIN <= m < 1.25


def test_learned_margin_clamps_a_wild_estimator(ring):
    for i in range(10):
        ring.add(_estimate_plan_span(i, 100, 500))
    m, _ev = planner_mod.learned_margin(1.25)
    assert m == planner_mod.MARGIN_MAX


def test_learned_margin_memoizes_until_ring_grows(ring):
    """The per-request ring scan is amortized: the learned value only
    refreshes after MARGIN_REFRESH new spans land in the ring (or the
    recorder instance changes — which is how each test's fresh ring
    gets a fresh computation)."""
    for i in range(20):
        ring.add(_estimate_plan_span(i, 1000, 1100))
    m1, ev1 = planner_mod.learned_margin(1.25)
    assert ev1["margin_learned"] is True
    # one wild new row, under the refresh threshold: memo hit
    ring.add(_estimate_plan_span(100, 1000, 5000))
    m2, _ = planner_mod.learned_margin(1.25)
    assert m2 == m1
    # past the threshold: recomputed, the spike is visible
    for i in range(planner_mod.MARGIN_REFRESH):
        ring.add(_estimate_plan_span(200 + i, 1000, 5000))
    m3, _ = planner_mod.learned_margin(1.25)
    assert m3 == planner_mod.MARGIN_MAX


def test_learned_margin_ignores_exact_and_garbage_rows(ring):
    ring.add(_FakeSpan(0, "sort.plan", {"decisions": {"cap": {
        "trigger": "exact", "predicted": {"need": 10},
        "actual": {"need": 99}}}}))
    ring.add(_FakeSpan(1, "sort.plan", {"decisions": "nope"}))
    ring.add(_FakeSpan(2, "verify", {}))
    m, ev = planner_mod.learned_margin(1.25)
    assert m == 1.25 and ev["margin_samples"] == 0


# ------------------------------------------------- serve-tuner hysteresis

def _feed(tuner, gap_s: float, n: int = 256, count: int = 24,
          t0: float = 0.0) -> float:
    t = t0
    for _ in range(count):
        tuner.observe(t, n)
        t += gap_s
    return t


def test_tuner_recommends_from_interarrival_gaps():
    tuner = planner_mod.ServeTuner(window=32, hysteresis=1.5,
                                   batch_keys=1 << 16,
                                   initial_window_s=1e-3)
    _feed(tuner, 2e-3)
    verdict = tuner.evaluate()
    assert verdict is not None
    _action, rec = verdict
    assert rec["window_s"] == pytest.approx(
        planner_mod.WINDOW_GAIN * 2e-3, rel=0.01)
    assert rec["p99_n"] == 256


def test_tuner_clamps_p99_to_batch_keys():
    """Over-batch_keys requests dispatch solo and never use a packed
    executable — their sizes must not steer bucket prewarm toward
    shapes no batch can ever select."""
    tuner = planner_mod.ServeTuner(window=32, hysteresis=1.5,
                                   batch_keys=1024,
                                   initial_window_s=1e-3)
    _feed(tuner, 2e-3, n=10_000_000)
    verdict = tuner.evaluate()
    assert verdict is not None
    rec = verdict[1]
    assert rec["p99_n"] == 1024
    assert rec["expected_batch_keys"] <= 1024


def test_tuner_commits_only_after_two_agreeing_evaluations():
    tuner = planner_mod.ServeTuner(window=32, hysteresis=1.5,
                                   batch_keys=1 << 16,
                                   initial_window_s=1e-3)
    t = _feed(tuner, 2e-3)
    a1 = tuner.evaluate()
    assert a1 is not None and a1[0] == "hold"      # phase one: armed
    assert tuner.window_s == 1e-3                  # nothing applied yet
    _feed(tuner, 2e-3, t0=t)
    a2 = tuner.evaluate()
    assert a2 is not None and a2[0] == "retune"    # phase two: commit
    assert tuner.window_s == pytest.approx(8e-3, rel=0.01)
    assert tuner.retunes == 1


def test_tuner_holds_inside_the_hysteresis_band():
    tuner = planner_mod.ServeTuner(window=32, hysteresis=1.5,
                                   batch_keys=1 << 16,
                                   initial_window_s=7e-3)
    t = _feed(tuner, 2e-3)          # desired 8 ms vs current 7 ms
    for _ in range(3):
        v = tuner.evaluate()
        assert v is not None and v[0] == "hold"
        t = _feed(tuner, 2e-3, t0=t)
    assert tuner.retunes == 0


def test_tuner_oscillating_mix_never_flips_twice_in_a_row():
    """The hysteresis regression contract: alternating bursty/sparse
    evaluations disagree in direction every time, so the window NEVER
    commits; and after any commit the immediately-following evaluation
    cannot commit again (two agreeing evaluations are required)."""
    tuner = planner_mod.ServeTuner(window=24, hysteresis=1.5,
                                   batch_keys=1 << 16,
                                   initial_window_s=4e-3)
    t = 0.0
    for i in range(8):
        t = _feed(tuner, 0.5e-3 if i % 2 == 0 else 3.5e-3, t0=t)
        v = tuner.evaluate()
        assert v is not None and v[0] == "hold"
    assert tuner.retunes == 0
    # an in-band evaluation clears the armed direction the loop left
    t = _feed(tuner, 1e-3, t0=t)
    assert tuner.evaluate()[0] == "hold"
    # now converge (two agreeing evals commit once) ...
    t = _feed(tuner, 3.5e-3, t0=t)
    assert tuner.evaluate()[0] == "hold"
    t = _feed(tuner, 3.5e-3, t0=t)
    assert tuner.evaluate()[0] == "retune"
    # ... and the very next evaluation, even wildly out of band the
    # OTHER way, may only arm — never a second consecutive flip
    t = _feed(tuner, 0.25e-3, t0=t)
    assert tuner.evaluate()[0] == "hold"
    assert tuner.retunes == 1


def test_tuner_snapshot_is_json_shaped():
    tuner = planner_mod.ServeTuner(window=32, hysteresis=1.5,
                                   batch_keys=1 << 16,
                                   initial_window_s=1e-3)
    snap = tuner.snapshot()
    assert snap["retunes"] == 0 and snap["observations"] == 0
    assert snap["hysteresis"] == 1.5


# ------------------------------------- end-to-end: shadow / on (mesh8)

def test_shadow_is_byte_identical_and_logs_decisions(mesh8, rng):
    x = rng.integers(-2**31, 2**31 - 1, size=1 << 13, dtype=np.int32)
    out_off, tr_off = run_sort(x, algo="sample", mesh=mesh8,
                               SORT_PLANNER="off")
    out_sh, tr_sh = run_sort(x, algo="sample", mesh=mesh8,
                             SORT_PLANNER="shadow")
    assert out_off.tobytes() == out_sh.tobytes()
    assert "planner" not in tr_off.plan.decisions
    d = tr_sh.plan.decisions["planner"]
    assert d.predicted["applied"] is False
    assert d.chosen in planner_mod.PLANNER_POLICIES
    assert tr_sh.counters["planner"] == "shadow"


def test_passthrough_sorts_a_sorted_input_with_one_verify(mesh8):
    x = np.arange(-4096, 4096, dtype=np.int32)
    out, tr = run_sort(x, algo="radix", mesh=mesh8, SORT_PLANNER="on")
    assert np.array_equal(out, x)
    assert tr.counters["planner_passthrough"] == 1
    p = tr.plan
    assert p.decisions["planner"].chosen == "verify_passthrough"
    assert p.decisions["ladder"].chosen == "passthrough"
    assert p.decisions["planner"].regret == 0.0
    # no exchange ever ran: the probe/negotiation machinery was skipped
    assert "exchange_cap" not in tr.counters


def test_passthrough_miss_falls_through_to_a_real_sort(mesh8):
    # one local inversion hidden between the profile's strided samples:
    # the scorer reads sorted, the verifier says no, the ladder sorts
    x = np.arange(1 << 13, dtype=np.int32)
    x[5], x[6] = x[6], x[5]
    assert planner_mod.choose(
        plan_mod.profile_host_array(x), "radix",
        verify_on=True).policy == "verify_passthrough"
    out, tr = run_sort(x, algo="radix", mesh=mesh8, SORT_PLANNER="on")
    assert np.array_equal(out, np.sort(x))
    assert tr.counters["planner_passthrough_miss"] == 1
    assert "planner_passthrough" not in tr.counters
    d = tr.plan.decisions["planner"]
    assert d.actual["misses"] == 1
    assert d.regret == 1.0  # the wasted verify is the planner's cost


def test_planner_reroutes_near_sorted_to_sample(mesh8):
    x = near_sorted(1 << 13)
    out, tr = run_sort(x, algo="radix", mesh=mesh8, SORT_PLANNER="on")
    assert np.array_equal(out, np.sort(x))
    p = tr.plan
    assert p.decisions["planner"].chosen == "merge_sample"
    assert p.decisions["algo"].chosen == "sample"
    assert p.decisions["algo"].requested == "radix"
    assert p.decisions["algo"].trigger == "planner"
    assert p.algo == "sample"


def test_planner_off_requires_plan_provenance(mesh8, rng):
    # the planner rides the plan record: SORT_PLAN=off disables it too
    x = rng.integers(-2**31, 2**31 - 1, size=4096, dtype=np.int32)
    out, tr = run_sort(x, mesh=mesh8, SORT_PLANNER="on", SORT_PLAN="off")
    assert np.array_equal(out, np.sort(x))
    assert tr.plan is None
    assert "planner" not in tr.counters


def test_learned_margin_is_wired_into_the_cap_decision(mesh8, rng,
                                                       monkeypatch):
    monkeypatch.setattr(planner_mod, "learned_margin",
                        lambda default, last_n=None:
                        (1.05, {"margin_samples": 20,
                                "margin_learned": True}))
    x = rng.integers(-2**31, 2**31 - 1, size=1 << 13, dtype=np.int32)
    out, tr = run_sort(x, algo="sample", mesh=mesh8, SORT_PLANNER="on")
    assert np.array_equal(out, np.sort(x))
    cap = tr.plan.decisions["cap"]
    assert cap.trigger == "estimate"
    assert cap.predicted["margin"] == 1.05
    assert tr.plan.decisions["planner"].predicted["margin"] == 1.05


def test_ladder_recovers_when_a_planner_choice_faults(mesh8):
    """A planner-chosen path that faults at dispatch must recover
    through the ordinary supervisor machinery — the planner may only
    choose among recoverable paths."""
    x = near_sorted(1 << 13, seed=3)
    out, tr = run_sort(x, algo="radix", mesh=mesh8, SORT_PLANNER="on",
                       SORT_FAULTS="dispatch_error:1",
                       SORT_MAX_RETRIES="2")
    assert np.array_equal(out, np.sort(x))
    p = tr.plan
    assert p.decisions["planner"].chosen == "merge_sample"
    assert p.decisions["ladder"].actual.get("dispatch_retries", 0) >= 1
    assert tr.counters.get("sort_retries", 0) >= 1


# -------------------------------------------- serve tuner wiring (core)

def _mk_core(mesh, mode: str):
    from mpitest_tpu.serve.server import ServerCore

    with knobs.scoped_env(SORT_PLANNER=mode,
                          SORT_SERVE_BATCH_WINDOW_MS="1"):
        return ServerCore(mesh=mesh)


def test_server_tuner_applies_only_in_on_mode(mesh8, monkeypatch):
    rec = {"window_s": 0.008, "p50_gap_s": 0.002, "p99_n": 512,
           "expected_batch_keys": 2048}
    for mode, applied in (("on", True), ("shadow", False)):
        core = _mk_core(mesh8, mode)
        try:
            assert core.tuner is not None
            monkeypatch.setattr(core.tuner, "observe",
                                lambda t, n, dt="int32": True)
            monkeypatch.setattr(core.tuner, "evaluate",
                                lambda: ("retune", dict(rec)))
            # no background AOT compiles in a unit test — the spawn
            # itself (applied mode + missing buckets) is the behavior
            monkeypatch.setattr(core.cache, "prewarm",
                                lambda *a, **k: 0)
            before = core.batcher.window_s
            core._tuner_observe(512)
            if applied:
                assert core.batcher.window_s == pytest.approx(0.008)
                assert core.batcher.window_retunes == 1
            else:
                assert core.batcher.window_s == before
                assert core.batcher.window_retunes == 0
            # both modes record the registered planner decisions —
            # window_auto always, buckets_auto when the mix's buckets
            # are not yet compiled (a fresh core's cache is empty)
            ds = [s.attrs["decisions"]["planner"]
                  for s in core.tracer.spans.spans
                  if s.name == "sort.plan"
                  and (s.attrs.get("decisions") or {}).get("planner")]
            by = {d["chosen"]: d for d in ds}
            assert "window_auto" in by, "no window_auto decision"
            assert by["window_auto"]["predicted"]["applied"] is applied
            assert "buckets_auto" in by, "no buckets_auto decision"
            assert by["buckets_auto"]["predicted"]["applied"] is applied
            assert by["buckets_auto"]["predicted"]["buckets"]
        finally:
            core.batcher.stop(timeout=10.0)


def test_server_without_planner_has_no_tuner(mesh8):
    core = _mk_core(mesh8, "off")
    try:
        assert core.tuner is None
        core._tuner_observe(256)  # must be a no-op, never a crash
    finally:
        core.batcher.stop(timeout=10.0)


def test_server_solo_window_disables_tuner(mesh8):
    """An operator-configured solo-dispatch server (window 0) has no
    batching window to tune — SORT_PLANNER=on must never convert it
    into a batching server (the tuner's clamp floor could only ever
    override that explicit config, never restore it)."""
    from mpitest_tpu.serve.server import ServerCore

    with knobs.scoped_env(SORT_PLANNER="on",
                          SORT_SERVE_BATCH_WINDOW_MS="0"):
        core = ServerCore(mesh=mesh8)
    try:
        assert core.tuner is None
        core._tuner_observe(256)
        assert core.batcher.window_s == 0.0
        assert core.batcher.window_retunes == 0
    finally:
        core.batcher.stop(timeout=10.0)


# ------------------------------------- flight-recorder snapshot (ISSUE 14)

def test_snapshot_kinds_and_last_n_filtering():
    rec = flight_recorder.FlightRecorder(128, "/tmp")
    for i in range(20):
        rec.add(_FakeSpan(i, "sort.plan" if i % 2 == 0 else "verify",
                          {"i": i}))
    assert len(rec.snapshot()) == 20
    plans = rec.snapshot(kinds=("sort.plan",))
    assert len(plans) == 10
    assert all(d["name"] == "sort.plan" for d in plans)
    last = rec.snapshot(last_n=3, kinds=("sort.plan",))
    assert [d["attrs"]["i"] for d in last] == [14, 16, 18]
    assert rec.snapshot(last_n=0) == []


def test_snapshot_bounded_by_ring_capacity():
    rec = flight_recorder.FlightRecorder(8, "/tmp")
    for i in range(50):
        rec.add(_FakeSpan(i, "verify", {"i": i}))
    rows = rec.snapshot()
    assert len(rows) == 8
    assert [d["attrs"]["i"] for d in rows] == list(range(42, 50))


def test_snapshot_consistent_under_concurrent_append():
    """The satellite regression: snapshot() while another thread
    hammers add() must never raise (a raw ``list(deque)`` against a
    concurrent append raises ``deque mutated during iteration``) and
    every snapshot stays within capacity."""
    rec = flight_recorder.FlightRecorder(64, "/tmp")
    stop = threading.Event()
    errors: list[BaseException] = []

    def writer() -> None:
        i = 0
        try:
            while not stop.is_set():
                rec.add(_FakeSpan(i, "verify", {"i": i}))
                i += 1
        except BaseException as e:  # noqa: BLE001 — the assertion
            errors.append(e)

    threads = [threading.Thread(target=writer) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for _ in range(300):
            rows = rec.snapshot(last_n=32, kinds=("verify",))
            assert len(rows) <= 32
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert not errors, f"writer raised: {errors[0]!r}"


# ------------------------------------------------------ knob validation

def test_planner_knob_validation():
    with knobs.scoped_env(SORT_PLANNER="warp"):
        with pytest.raises(ValueError, match="SORT_PLANNER"):
            knobs.get("SORT_PLANNER")
    with knobs.scoped_env(SORT_PLANNER_WINDOW="4"):
        with pytest.raises(ValueError, match="SORT_PLANNER_WINDOW"):
            knobs.get("SORT_PLANNER_WINDOW")
    for bad in ("1.0", "0.5", "nan", "inf", "x"):
        with knobs.scoped_env(SORT_PLANNER_HYSTERESIS=bad):
            with pytest.raises(ValueError,
                               match="SORT_PLANNER_HYSTERESIS"):
                knobs.get("SORT_PLANNER_HYSTERESIS")
    # defaults: planner off, sane learning window
    assert knobs.get("SORT_PLANNER") == "off"
    # floor == planner.MIN_OBSERVATIONS: a smaller window would
    # validate but silently behave as 16 (the tuner's minimum)
    assert knobs.get("SORT_PLANNER_WINDOW") >= planner_mod.MIN_OBSERVATIONS
    assert knobs.get("SORT_PLANNER_HYSTERESIS") > 1.0


def test_planner_knobs_in_driver_validate_lists():
    """Both drivers fail fast on planner-knob garbage: the validate()
    sweeps must name all three knobs (source-level pin, like the
    exchange-engine knob's)."""
    for driver in ("drivers/sort_cli.py", "drivers/sort_server.py"):
        src = (REPO / driver).read_text()
        for name in ("SORT_PLANNER", "SORT_PLANNER_WINDOW",
                     "SORT_PLANNER_HYSTERESIS"):
            assert f'"{name}"' in src, f"{driver} does not validate {name}"
