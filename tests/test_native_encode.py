"""Native encode engine parity suite (ISSUE 6).

The one correctness contract of ``native/encode.c`` +
``mpitest_tpu/utils/native_encode.py``: for EVERY input — all ten
supported dtypes, randomized values, adversarial float payloads,
malformed text, wrong headers, chunk-boundary token splits — the native
engine must produce **bit-identical** outputs to the pure-Python engine
(words, per-word min/max, pad key, fingerprint) and raise the **same
typed errors** where the Python path raises.  The Python engine is the
oracle; ``SORT_NATIVE_ENCODE=off`` must therefore preserve seed
behavior exactly by construction.

Builds the engine library on demand (one small cc invocation, like the
other native tests build their binaries); skips — loudly, via the
standard marker — only when no C compiler exists.
"""

from __future__ import annotations

import shutil

import numpy as np
import pytest

from mpitest_tpu.ops.keys import codec_for
from mpitest_tpu.utils import io, knobs, native_encode

ALL_DTYPES = [np.int8, np.uint8, np.int16, np.uint16, np.int32, np.uint32,
              np.int64, np.uint64, np.float32, np.float64]

INT_DTYPES = [np.int8, np.uint8, np.int16, np.uint16, np.int32, np.uint32,
              np.int64, np.uint64]


@pytest.fixture(scope="module", autouse=True)
def engine_lib():
    """Build + load the native library once for the module."""
    if shutil.which("cc") is None and shutil.which("gcc") is None:
        pytest.skip("no C compiler on this image")
    if not native_encode.build():
        pytest.skip(f"libencode build failed: "
                    f"{native_encode.unavailable_reason()}")
    assert native_encode.available()


def _chunks(dtype, sizes=(1, 7, 1024, 4097), seed=5):
    dt = np.dtype(dtype)
    for i, n in enumerate(sizes):
        x = io.generate("uniform", n, dt, seed=seed + i)
        if dt.kind == "f" and n >= 8:
            x[:6] = [np.nan, -np.nan, -0.0, 0.0, np.inf, -np.inf]
        yield x


@pytest.mark.parametrize("dtype", ALL_DTYPES)
def test_encode_fold_parity(dtype):
    """words + min/max + pad key + fingerprint bit-identical across
    engines, randomized chunks at several sizes, both fold_fp modes."""
    codec = codec_for(np.dtype(dtype))
    for x in _chunks(dtype):
        for fold_fp in (True, False):
            wn, ln, hn, mn, fn = native_encode.encode_and_fold(
                x, codec, fold_fp, "native")
            wp, lp, hp, mp, fp = native_encode.encode_and_fold(
                x, codec, fold_fp, "python")
            assert len(wn) == len(wp) == codec.n_words
            for a, b in zip(wn, wp):
                assert a.dtype == np.uint32
                np.testing.assert_array_equal(a, b)
            assert ln == lp and hn == hp
            if not fold_fp:
                assert fn is None and fp is None
            else:
                assert fn == fp
            if np.dtype(dtype).kind == "f":
                assert mn is None and mp is None
            else:
                # same value AND same native dtype (the pad encode
                # re-encodes this scalar; a widened type would differ)
                assert mn == mp
                assert np.asarray(mn).dtype == np.asarray(mp).dtype


def test_encode_fold_empty_chunk_rejected():
    """n==0 has no min/max/pad: the SAME ValueError from both engines
    (the Python path would crash in w.min(), the native path would
    return inverted neutral folds — neither may leak out)."""
    codec = codec_for(np.dtype(np.int32))
    for eng in ("native", "python"):
        with pytest.raises(ValueError, match="empty chunk"):
            native_encode.encode_and_fold(np.empty(0, np.int32),
                                          codec, True, eng)


def test_load_is_thread_safe():
    """Concurrent first resolutions all see the completed verdict —
    never a half-written (_LOADED, _LIB) pair (a spurious 'unavailable'
    would silently degrade an auto run)."""
    import threading

    native_encode._LOADED = False
    native_encode._LIB = None
    native_encode._LIB_ERR = None
    results: list = []
    barrier = threading.Barrier(8)

    def resolve() -> None:
        barrier.wait()
        results.append(native_encode.engine())

    threads = [threading.Thread(target=resolve) for _ in range(8)]
    with knobs.scoped_env(SORT_NATIVE_ENCODE="auto"):
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert results == ["native"] * 8


def test_encode_fold_noncontiguous_input():
    """Strided views normalize before the C call (same values out)."""
    codec = codec_for(np.dtype(np.int32))
    base = io.generate("uniform", 2048, np.int32, seed=1)
    view = base[::2]
    assert not view.flags.c_contiguous
    wn, ln, hn, mn, fn = native_encode.encode_and_fold(
        view, codec, True, "native")
    wp, lp, hp, mp, fp = native_encode.encode_and_fold(
        np.ascontiguousarray(view), codec, True, "python")
    np.testing.assert_array_equal(wn[0], wp[0])
    assert (ln, hn, mn, fn) == (lp, hp, mp, fp)


def test_encode_fold_misaligned_input():
    """A contiguous-but-misaligned buffer (np.frombuffer at an odd
    offset) normalizes before the C call — unaligned 64-bit loads in
    the kernel would be UB."""
    codec = codec_for(np.dtype(np.int64))
    raw = io.generate("uniform", 257, np.int64, seed=8).tobytes()
    mis = np.frombuffer(b"\0" * 4 + raw, dtype=np.int64, offset=4)
    assert mis.flags.c_contiguous and not mis.flags.aligned
    wn, ln, hn, mn, fn = native_encode.encode_and_fold(
        mis, codec, True, "native")
    wp, lp, hp, mp, fp = native_encode.encode_and_fold(
        np.ascontiguousarray(mis), codec, True, "python")
    for a, b in zip(wn, wp):
        np.testing.assert_array_equal(a, b)
    assert (ln, hn, mn, fn) == (lp, hp, mp, fp)


@pytest.mark.parametrize("dtype", INT_DTYPES)
def test_parse_parity_valid(dtype):
    """Randomized valid decimal streams parse to identical arrays,
    dtype truncation semantics included."""
    dt = np.dtype(dtype)
    x = io.generate("uniform", 1500, dt, seed=17)
    block = ("\n".join(str(v) for v in x.tolist())
             + " +17 -0 0 \t 9 ").encode()
    a = native_encode.parse_text_tokens(block, dt, "native")
    b = native_encode.parse_text_tokens(block, dt, "python")
    assert a.dtype == dt == b.dtype
    np.testing.assert_array_equal(a, b)


def test_parse_parity_boundaries():
    cases = [
        (b"9223372036854775807 -9223372036854775808", np.int64),
        (b"18446744073709551615 0 -0", np.uint64),
        (b"2147483648 -2147483649", np.int32),  # int64-truncation wrap
        (b"", np.int64),
        (b"   \n\t ", np.int64),
        # PEP-515 underscores: the Python engine's cast routes through
        # int(), which ACCEPTS digit-grouping underscores — so must C
        (b"1_0 1_000_000 +4_2 -9_9", np.int64),
        (b"18_446_744_073_709_551_615", np.uint64),
    ]
    for blk, dt in cases:
        a = native_encode.parse_text_tokens(blk, np.dtype(dt), "native")
        b = native_encode.parse_text_tokens(blk, np.dtype(dt), "python")
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("block,dtype,exc", [
    (b"1 abc 3", np.int64, ValueError),           # truncated/garbage token
    (b"1.5", np.int32, ValueError),               # float literal
    (b"0x10", np.int32, ValueError),              # non-decimal base
    (b"--3", np.int32, ValueError),               # doubled sign
    (b"+ 1", np.int32, ValueError),               # bare sign token
    (b"1__0", np.int32, ValueError),              # doubled underscore
    (b"1_", np.int32, ValueError),                # trailing underscore
    (b"_1", np.int32, ValueError),                # leading underscore
    (b"99999999999999999999x", np.int64, ValueError),   # garbage outranks
    (b"99999999999999999999999_", np.int64, ValueError),  # ...overflow
    (b"99999999999999999999999", np.int64, OverflowError),
    (b"9223372036854775808", np.int64, OverflowError),
    (b"-9223372036854775809", np.int64, OverflowError),
    (b"-1", np.uint64, OverflowError),
    (b"18446744073709551616", np.uint64, OverflowError),
])
def test_parse_same_typed_errors(block, dtype, exc):
    """Malformed input raises the SAME exception type from both engines
    (the ISSUE 6 parity-gate contract for error paths)."""
    for eng in ("native", "python"):
        with pytest.raises(exc):
            native_encode.parse_text_tokens(block, np.dtype(dtype), eng)


@pytest.mark.parametrize("dtype", [np.int32, np.uint64])
def test_chunk_boundary_splits_native(dtype, tmp_path):
    """iter_key_chunks under the FORCED native engine with block
    boundaries landing mid-token: concatenation equals the monolithic
    read (the carry logic feeds whole tokens to the C parser)."""
    dt = np.dtype(dtype)
    x = io.generate("uniform", 1000, dt, seed=11)
    p = str(tmp_path / "keys.txt")
    io.write_keys_text(p, x)
    with knobs.scoped_env(SORT_NATIVE_ENCODE="on"):
        chunks = list(io.iter_key_chunks(p, dt, chunk_elems=3))
    assert len(chunks) > 10
    np.testing.assert_array_equal(np.concatenate(chunks), x)
    with knobs.scoped_env(SORT_NATIVE_ENCODE="off"):
        ref = list(io.iter_key_chunks(p, dt, chunk_elems=3))
    np.testing.assert_array_equal(np.concatenate(ref),
                                  np.concatenate(chunks))


def test_header_parity(tmp_path):
    """SORTBIN1 header validation: identical ValueError MESSAGES from
    both engines for bad magic, wrong kind, wrong width; reads through
    io.py hit the engine-dispatched check."""
    good = io.BIN_MAGIC + b"i" + bytes([4]) + b"\0" * 6
    bad_magic = b"SORTBIN9" + b"i" + bytes([4]) + b"\0" * 6
    wrong_kind = io.BIN_MAGIC + b"u" + bytes([4]) + b"\0" * 6
    wrong_size = io.BIN_MAGIC + b"i" + bytes([8]) + b"\0" * 6
    garbage_kind = io.BIN_MAGIC + bytes([0xFF, 4]) + b"\0" * 6
    for hdr in (bad_magic, wrong_kind, wrong_size, garbage_kind):
        msgs = []
        for eng in ("native", "python"):
            try:
                native_encode.check_bin_header(hdr, "f.bin",
                                               np.dtype(np.int32), eng)
                msgs.append(None)
            except ValueError as e:
                msgs.append(str(e))
        assert msgs[0] is not None and msgs[0] == msgs[1], (hdr, msgs)
    for eng in ("native", "python"):
        native_encode.check_bin_header(good, "f.bin", np.dtype(np.int32),
                                       eng)  # no raise
    # end to end through the reader, engine forced on: same hard error
    p = str(tmp_path / "k.bin")
    io.write_keys_binary(p, np.arange(10, dtype=np.int32))
    with knobs.scoped_env(SORT_NATIVE_ENCODE="on"):
        with pytest.raises(ValueError, match="holds i32 keys, not int64"):
            io.read_keys_binary(p, np.int64)


def test_knob_selects_engine(monkeypatch):
    """off -> python; on without a loadable library -> loud RuntimeError
    (never a silent fallback); auto without the library -> python."""
    with knobs.scoped_env(SORT_NATIVE_ENCODE="off"):
        assert native_encode.engine() == "python"
    with knobs.scoped_env(SORT_NATIVE_ENCODE="on"):
        assert native_encode.engine() == "native"
    # simulate a missing/stale library
    monkeypatch.setattr(native_encode, "_LOADED", True)
    monkeypatch.setattr(native_encode, "_LIB", None)
    monkeypatch.setattr(native_encode, "_LIB_ERR", "forced by test")
    with knobs.scoped_env(SORT_NATIVE_ENCODE="auto"):
        assert native_encode.engine() == "python"
    with knobs.scoped_env(SORT_NATIVE_ENCODE="on"):
        with pytest.raises(RuntimeError, match="forced by test"):
            native_encode.engine()
    with knobs.scoped_env(SORT_NATIVE_ENCODE="garbage"):
        with pytest.raises(ValueError, match="SORT_NATIVE_ENCODE="):
            native_encode.engine()


def test_streamed_pipeline_parity_across_engines(mesh4, tmp_path):
    """The full streamed pipeline (mmap -> encode pool -> sharded words)
    lands bit-identical device words, fingerprint and planner diffs
    under both engines, and the chosen engine is visible in the stats."""
    from mpitest_tpu.models.ingest import stream_to_mesh

    x = io.generate("uniform", 50_000, np.int32, seed=23)
    p = str(tmp_path / "k.bin")
    io.write_keys_binary(p, x)
    staged = {}
    for mode in ("off", "on"):
        with knobs.scoped_env(SORT_NATIVE_ENCODE=mode,
                              SORT_INGEST_CHUNK="9000"):
            mm = io.open_keys_mmap(p)
            staged[mode] = stream_to_mesh(mm, mesh4)
    assert staged["off"].stats.encode_engine == "python"
    assert staged["on"].stats.encode_engine == "native"
    assert staged["off"].fingerprint == staged["on"].fingerprint
    assert staged["off"].word_diffs == staged["on"].word_diffs
    for a, b in zip(staged["off"].words, staged["on"].words):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
