"""Request-lifecycle robustness tests (ISSUE 11) — named to sort last
like the other zz suites (tier-1 is timeout-bound).

Covers: deadline parsing/propagation/expiry at each lifecycle stage
(admission, queue, dispatch), the dispatch watchdog's trip → half-open
→ recover machine (stub-level AND against a real wedged dispatch via
the ``dispatch_stall`` fault site), client retry/backoff and hedging
against an in-process flaky server, the wire-fault spec round trip and
the chaos proxy, wire read timeouts + the admission-byte-release
regression (client killed mid-payload), and the new knob contracts.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from contextlib import contextmanager

import numpy as np
import pytest

from mpitest_tpu import faults
from mpitest_tpu.serve.batching import ERR_DEADLINE, Batcher, ServeRequest
from mpitest_tpu.serve.client import (ResilientClient, ServeClient,
                                      ServeReply, reply_fingerprint_ok)
from mpitest_tpu.serve.watchdog import CircuitBreaker
from mpitest_tpu.utils import flight_recorder, knobs
from mpitest_tpu.utils.spans import SpanLog


@contextmanager
def serve_core(**env):
    """A ServerCore configured via scoped knobs; dispatch thread (and
    watchdog, if started) stopped at exit."""
    from mpitest_tpu.serve.server import ServerCore

    with knobs.scoped_env(**env):
        core = ServerCore()
        try:
            yield core
        finally:
            core.watchdog.stop()
            core.batcher.stop(timeout=10)


@contextmanager
def wire_server(core):
    """An in-process TCP front over ``core`` (real sockets, real
    handler threads — the layer the wire timeouts live in)."""
    from mpitest_tpu.serve.server import SortServer

    srv = SortServer(core, "127.0.0.1", 0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield srv.bound_port
    finally:
        srv.shutdown()
        srv.server_close()


def _req(arr, **kw):
    defaults = dict(arr=arr, dtype=np.dtype(arr.dtype), algo="sample",
                    batchable=True, trace_id="t")
    defaults.update(kw)
    return ServeRequest(**defaults)


def wait_until(pred, timeout_s=10.0, interval=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ------------------------------------------------------ wire-fault spec

def test_wire_fault_spec_round_trip():
    fs = faults.parse_wire_faults(
        "wire_torn_header@3, wire_delay_response@200:4,"
        "wire_connect_silence")
    assert [f.site for f in fs] == ["wire_torn_header",
                                    "wire_delay_response",
                                    "wire_connect_silence"]
    assert fs[1].param == 200 and fs[1].every == 4
    # canonical spec round-trips through the parser
    again = faults.parse_wire_faults(",".join(f.spec() for f in fs))
    assert again == fs
    # defaults fill in
    assert faults.parse_wire_faults("wire_stall_payload")[0].param == \
        faults.WIRE_DEFAULT_PARAM["wire_stall_payload"]
    # every-cadence: every=4 fires on the 4th, 8th, ... (0-based 3, 7)
    f = faults.parse_wire_faults("wire_delay_response:4")[0]
    assert [i for i in range(9) if f.fires_on(i)] == [3, 7]


def test_wire_fault_spec_rejects_garbage():
    with pytest.raises(ValueError, match="unknown site"):
        faults.parse_wire_faults("wire_nonsense")
    with pytest.raises(ValueError, match="bad param"):
        faults.parse_wire_faults("wire_torn_header@x")
    with pytest.raises(ValueError, match="bad every-count"):
        faults.parse_wire_faults("wire_torn_header:0")
    with pytest.raises(ValueError, match="empty spec"):
        faults.parse_wire_faults(" , ")


def test_dispatch_stall_site_registered():
    # the watchdog drill site rides the ordinary registry/grid
    assert "dispatch_stall" in faults.SITES
    reg = faults.FaultRegistry("dispatch_stall")
    assert reg.would_fire("dispatch_stall")


# ------------------------------------------------------------ deadlines

def test_serve_request_deadline_helpers():
    a = np.arange(4, dtype=np.int32)
    r = _req(a)
    assert not r.expired()
    r = _req(a, deadline=time.monotonic() - 0.01)
    assert r.expired()
    r.fail_deadline("queue")
    assert r.done.is_set()
    assert r.error[0] == ERR_DEADLINE == "deadline_exceeded"
    assert r.deadline_stage == "queue"


def test_deadline_expiry_at_admission_stage(rng):
    with serve_core(SORT_SERVE_BATCH_WINDOW_MS="0") as core:
        a = rng.integers(-2**31, 2**31 - 1, size=256, dtype=np.int32)
        st, detail, attrs = core.execute(a, deadline_ms=1e-4)
        assert st == "deadline_exceeded"
        assert attrs["deadline_stage"] == "admission"
        # admission bytes provably released
        assert core.admission.inflight_bytes == 0
        assert core.admission.inflight == 0
        # the registered audit event fired with the stage
        ev = [s for s in core.tracer.spans.spans
              if s.name == "serve.deadline"]
        assert ev and ev[-1].attrs["stage"] == "admission"
        # an un-deadlined request still flows
        st2, out, _ = core.execute(a)
        assert st2 == "ok" and np.array_equal(out, np.sort(a))


def test_deadline_expiry_in_queue_and_window_close():
    """Stub-executor batcher: a request whose deadline dies while a
    slow dispatch holds the thread is cancelled at pickup (stage
    queue, never handed to an executor), and the batch window closes
    at the earliest member deadline instead of the full window."""
    dispatched: list[str] = []

    def run_batch(reqs):
        dispatched.extend(r.trace_id for r in reqs)
        time.sleep(0.3)          # the slow dispatch the victim queues behind
        for r in reqs:
            r.complete(r.arr, batched=True, bucket=None)

    def run_solo(req):
        dispatched.append(req.trace_id)
        req.complete(req.arr, batched=False, bucket=None)

    a = np.arange(8, dtype=np.int32)
    b = Batcher(run_batch, run_solo, window_s=0.0, batch_keys=1 << 16)
    try:
        first = _req(a, trace_id="slow")
        b.submit(first)
        victim = _req(a, trace_id="victim",
                      deadline=time.monotonic() + 0.05)
        b.submit(victim)
        assert victim.done.wait(5.0)
        assert victim.error[0] == ERR_DEADLINE
        assert victim.deadline_stage == "queue"
        assert first.done.wait(5.0) and first.error is None
        assert "victim" not in dispatched       # never dispatched
        assert b.deadline_cancelled == 1
    finally:
        b.stop(timeout=5)

    # earliest-member deadline closes the pack window early
    t_dispatch: list[float] = []

    def run_batch2(reqs):
        t_dispatch.append(time.monotonic())
        for r in reqs:
            r.complete(r.arr, batched=True, bucket=None)

    b2 = Batcher(run_batch2, run_solo, window_s=10.0, batch_keys=1 << 16)
    try:
        t0 = time.monotonic()
        hurried = _req(a, trace_id="hurried",
                       deadline=time.monotonic() + 0.15)
        b2.submit(hurried)
        assert hurried.done.wait(5.0)
        assert hurried.error is None            # dispatched, not expired
        assert t_dispatch and t_dispatch[0] - t0 < 5.0, \
            "window ignored the member deadline"
        assert t_dispatch[0] - t0 < 1.0
    finally:
        b2.stop(timeout=5)


def test_deadline_wire_parse_and_propagation(rng, mesh8):
    import io

    with serve_core(SORT_SERVE_BATCH_WINDOW_MS="0") as core:
        a = rng.integers(-2**31, 2**31 - 1, size=128, dtype=np.int32)

        def wire(hdr_extra, payload=None):
            hdr = {"v": "sortserve.v1", "dtype": "int32",
                   "n": int(a.size), **hdr_extra}
            body = a.tobytes() if payload is None else payload
            return core.handle_wire(
                json.dumps(hdr).encode() + b"\n", io.BytesIO(body))

        # garbage deadline_ms is a typed wire error, framing kept
        for bad in ("soon", -5, 0, float("nan"), True):
            resp, _p, keep = wire({"deadline_ms": bad})
            assert not resp["ok"] and resp["error"] == "bad_request"
            assert keep is True, bad
        # generous deadline: served normally
        resp, payload, keep = wire({"deadline_ms": 60000})
        assert resp["ok"] and keep
        assert np.array_equal(np.frombuffer(payload, np.int32),
                              np.sort(a))
        # microscopic deadline: typed deadline_exceeded, bytes released
        resp, payload, keep = wire({"deadline_ms": 1e-4})
        assert not resp["ok"]
        assert resp["error"] == "deadline_exceeded"
        assert payload == b"" and keep
        assert core.admission.inflight_bytes == 0


def test_executor_entry_deadline_gate(rng):
    """Stage 'dispatch': a request that expires between queue pickup
    and executor entry is cancelled inside the executor wrapper."""
    with serve_core(SORT_SERVE_BATCH_WINDOW_MS="0") as core:
        a = rng.integers(-2**31, 2**31 - 1, size=64, dtype=np.int32)
        req = _req(a, batchable=False,
                   deadline=time.monotonic() - 0.01)
        core._run_solo(req)
        assert req.error[0] == ERR_DEADLINE
        assert req.deadline_stage == "dispatch"


# ------------------------------------------------- watchdog + breaker

def test_circuit_breaker_state_machine():
    br = CircuitBreaker(backoff_s=0.05)
    assert br.state == "closed" and not br.engaged()
    assert br.trip() is True
    assert br.trip() is False            # already open: one incident
    assert br.engaged() and br.state == "open"
    assert not br.ready_to_probe()       # backoff not elapsed
    time.sleep(0.06)
    assert br.ready_to_probe()
    assert br.state == "half_open" and br.engaged()
    br.probe_failed()                    # backoff doubles, reopens
    assert br.state == "open"
    assert br.snapshot()["backoff_s"] == pytest.approx(0.1)
    time.sleep(0.11)
    assert br.ready_to_probe()
    br.probe_succeeded()
    assert br.state == "closed" and not br.engaged()
    assert br.trips == 1 and br.recoveries == 1


def test_watchdog_trips_on_wedged_dispatch_and_recovers(
        rng, mesh8, tmp_path):
    """The real thing end to end: a per-request ``dispatch_stall``
    wedges the REAL dispatch thread (distributed path, supervisor
    dispatch); the watchdog must trip the breaker (typed fast
    rejections, flight-recorder artifact), fail queued work typed, and
    the half-open probe must recover WITHOUT a restart."""
    flight_recorder.reset()
    try:
        with serve_core(SORT_SERVE_ALLOW_FAULTS="1",
                        SORT_FAULT_STALL_MS="1500",
                        SORT_SERVE_DISPATCH_TIMEOUT_S="0.3",
                        SORT_SERVE_BREAKER_BACKOFF_S="0.2",
                        SORT_SERVE_BATCH_WINDOW_MS="0",
                        SORT_FLIGHT_RECORDER_DIR=str(tmp_path),
                        ) as core:
            core.start_watchdog()
            a = rng.integers(-2**31, 2**31 - 1, size=2048,
                             dtype=np.int32)
            st, out, _ = core.execute(a)          # warm the programs
            assert st == "ok"
            res: dict = {}

            def stalled():
                res["r"] = core.execute(a, faults_spec="dispatch_stall")

            t = threading.Thread(target=stalled, daemon=True)
            t.start()
            assert wait_until(lambda: core.breaker.state != "closed",
                              5.0), "watchdog never tripped"
            # while engaged: admission is a FAST typed rejection
            st2, detail, attrs = core.execute(a)
            assert st2 == "backpressure"
            assert attrs["reject"] == "breaker"
            # the wedge clears (~1.5s); the probe must close the breaker
            assert wait_until(lambda: core.breaker.state == "closed",
                              15.0), "breaker never recovered"
            t.join(timeout=30)
            assert res["r"][0] == "ok"    # the stalled sort completed
            st3, out3, _ = core.execute(a)
            assert st3 == "ok" and np.array_equal(out3, np.sort(a))
            # audit trail: trip + recovered events, counted trips
            events = [s.attrs.get("event") for s in
                      core.tracer.spans.spans
                      if s.name == "serve.watchdog"]
            assert "trip" in events and "recovered" in events
            assert core.breaker.trips == 1
            assert core.metrics.counter(
                "sort_serve_watchdog_trips_total").get() == 1
            # the incident artifact exists and is schema-clean
            arts = sorted(tmp_path.glob("flight-*-watchdog-*.jsonl"))
            assert arts, "watchdog trip wrote no flight artifact"
            from mpitest_tpu.report import check_rows, load_rows

            assert check_rows(load_rows(str(arts[-1]))) == []
    finally:
        flight_recorder.reset()


def test_watchdog_fails_queued_requests_typed():
    """While the dispatch thread is wedged, queued work is failed
    typed 'internal' by the trip — nobody burns the completion
    timeout on a corpse (stub executors, no jax)."""
    import types

    release = threading.Event()

    def run_solo(req):
        release.wait(10.0)
        req.complete(req.arr, batched=False, bucket=None)

    def run_batch(reqs):
        for r in reqs:
            run_solo(r)

    b = Batcher(run_batch, run_solo, window_s=0.0, batch_keys=1 << 16)
    from mpitest_tpu.serve.watchdog import DispatchWatchdog
    from mpitest_tpu.utils.trace import Tracer

    core = types.SimpleNamespace(batcher=b, tracer=Tracer(),
                                 default_algo="sample")
    br = CircuitBreaker(backoff_s=30.0)   # no probe during the test
    wd = DispatchWatchdog(core, timeout_s=0.2, breaker=br)
    try:
        a = np.arange(8, dtype=np.int32)
        wedged = _req(a, trace_id="wedged", batchable=False)
        queued = _req(a, trace_id="queued", batchable=False)
        b.submit(wedged)
        b.submit(queued)
        wd.start()
        assert queued.done.wait(5.0), "queued request never failed"
        assert queued.error[0] == "internal"
        assert "watchdog" in queued.error[1]
        assert br.state == "open" and br.trips == 1
        release.set()
        assert wedged.done.wait(5.0) and wedged.error is None
    finally:
        release.set()
        wd.stop()
        b.stop(timeout=5)


def test_batcher_stop_reports_wedged_thread():
    """The drain-path regression (ISSUE 11 satellite): stop() must
    return False while a dispatch is wedged — the silently-discarded
    join() outcome that let drain_and_stop report a clean exit."""
    release = threading.Event()

    def run_solo(req):
        release.wait(10.0)
        req.complete(req.arr, batched=False, bucket=None)

    b = Batcher(lambda reqs: None, run_solo, window_s=0.0,
                batch_keys=1 << 16)
    try:
        b.submit(_req(np.arange(4, dtype=np.int32), batchable=False))
        time.sleep(0.1)
        assert b.stop(timeout=0.2) is False
        release.set()
        assert b.stop(timeout=5.0) is True
    finally:
        release.set()


# ------------------------------------------------------- wire timeouts

def test_stalled_mid_payload_disconnected_and_bytes_released(rng):
    """THE regression (ISSUE 11 satellite): a client that stalls (or
    dies) mid-payload used to pin a handler thread and its admitted
    byte budget until process death.  Now: disconnected within the
    read timeout, ``sort_serve_inflight_bytes`` back to 0."""
    with serve_core(SORT_SERVE_READ_TIMEOUT_S="0.5",
                    SORT_SERVE_BATCH_WINDOW_MS="0") as core:
        with wire_server(core) as port:
            a = rng.integers(-2**31, 2**31 - 1, size=1 << 14,
                             dtype=np.int32)
            hdr = json.dumps({"v": "sortserve.v1", "dtype": "int32",
                              "n": int(a.size)}).encode() + b"\n"
            # variant 1: stall silently mid-payload, connection open
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            s.sendall(hdr + a.tobytes()[: a.nbytes // 2])
            assert wait_until(lambda: core.admission.inflight_bytes > 0,
                              5.0), "request never admitted"
            t0 = time.monotonic()
            assert wait_until(
                lambda: core.admission.inflight_bytes == 0, 5.0), \
                "admission bytes leaked on a stalled payload"
            assert time.monotonic() - t0 < 4.0
            assert core.metrics.counter(
                "sort_serve_timeouts_total").get(kind="read") >= 1
            s.close()
            # variant 2: killed mid-payload (abrupt close)
            s2 = socket.create_connection(("127.0.0.1", port),
                                          timeout=10)
            s2.sendall(hdr + a.tobytes()[: a.nbytes // 2])
            s2.close()
            assert wait_until(
                lambda: core.admission.inflight_bytes == 0, 5.0)
            # the server still serves
            x = rng.integers(-2**31, 2**31 - 1, size=600,
                             dtype=np.int32)
            with ServeClient("127.0.0.1", port, timeout=30) as c:
                r = c.sort(x)
            assert r.ok and np.array_equal(r.arr, np.sort(x))


def test_idle_connection_closed(rng):
    with serve_core(SORT_SERVE_IDLE_TIMEOUT_S="0.3",
                    SORT_SERVE_BATCH_WINDOW_MS="0") as core:
        with wire_server(core) as port:
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            s.settimeout(5.0)
            # say nothing; the server must hang up within the idle bound
            assert s.recv(1) == b""
            s.close()
            assert core.metrics.counter(
                "sort_serve_timeouts_total").get(kind="idle") >= 1


# ----------------------------------------------------- client resilience

class _FlakyHandler(socketserver.StreamRequestHandler):
    """Protocol-speaking flaky server: behavior by connection index via
    server.plan — 'die' (close at accept), 'backpressure' (typed
    rejection), 'stall' (hold the reply), int/float seconds, 'ok'."""

    def handle(self):
        srv = self.server
        with srv.lock:
            idx = srv.conn_seq
            srv.conn_seq += 1
        mode = srv.plan[min(idx, len(srv.plan) - 1)]
        if mode == "die":
            return
        while True:
            line = self.rfile.readline()
            if not line.strip():
                return
            hdr = json.loads(line)
            n, dt = hdr["n"], np.dtype(hdr["dtype"])
            arr = np.frombuffer(self.rfile.read(n * dt.itemsize), dt)
            if mode == "backpressure":
                self.wfile.write(json.dumps(
                    {"ok": False, "error": "backpressure",
                     "detail": "induced",
                     "trace_id": hdr.get("trace_id")}).encode() + b"\n")
                self.wfile.flush()
                continue
            if isinstance(mode, (int, float)):
                time.sleep(float(mode))
            out = np.sort(arr)
            self.wfile.write(json.dumps(
                {"ok": True, "n": n, "dtype": dt.name,
                 "trace_id": hdr.get("trace_id")}).encode() + b"\n"
                + out.tobytes())
            self.wfile.flush()


@contextmanager
def flaky_server(plan):
    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0),
                                          _FlakyHandler)
    srv.daemon_threads = True
    srv.plan = plan
    srv.conn_seq = 0
    srv.lock = threading.Lock()
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        yield srv.server_address[1]
    finally:
        srv.shutdown()
        srv.server_close()


def test_client_retries_connect_errors_with_backoff(rng):
    a = rng.integers(-2**31, 2**31 - 1, size=300, dtype=np.int32)
    with flaky_server(["die", "die", "ok"]) as port:
        c = ResilientClient("127.0.0.1", port, backoff_s=0.01,
                            max_attempts=4)
        r = c.sort(a)
        assert r.ok and np.array_equal(r.arr, np.sort(a))
        assert c.stats["retries"] == 2
        assert c.stats["transport_errors"] == 2


def test_client_retries_typed_retryable_and_respects_budget(rng):
    a = rng.integers(-2**31, 2**31 - 1, size=300, dtype=np.int32)
    with flaky_server(["backpressure", "ok"]) as port:
        c = ResilientClient("127.0.0.1", port, backoff_s=0.01,
                            max_attempts=3)
        r = c.sort(a)
        assert r.ok and c.stats["retries"] == 1
    # budget exhausted on a persistently-backpressured server: the
    # typed reply is returned, never an infinite loop
    with flaky_server(["backpressure"]) as port:
        c = ResilientClient("127.0.0.1", port, backoff_s=0.01,
                            max_attempts=2)
        r = c.sort(a)
        assert not r.ok and r.error == "backpressure"
        assert c.stats["retries"] == 1
    # non-retryable typed errors come straight back
    with serve_core(SORT_SERVE_BATCH_WINDOW_MS="0") as core:
        with wire_server(core) as port:
            c = ResilientClient("127.0.0.1", port, max_attempts=3)
            r = c.sort(a, algo=None, trace_id="bad id!" )
            # the server rejects the malformed trace id typed; the
            # client must NOT burn retries on it
            assert not r.ok and r.error == "bad_request"
            assert c.stats["retries"] == 0


def test_client_deadline_budget_shrinks_across_retries(rng):
    """The end-to-end deadline is ONE budget: elapsed backoff and
    failed attempts shrink what later attempts send, and once spent
    the client fails locally typed — it never hands the server a
    fresh full deadline per retry."""
    a = rng.integers(-2**31, 2**31 - 1, size=64, dtype=np.int32)
    with flaky_server(["backpressure"]) as port:
        c = ResilientClient("127.0.0.1", port, backoff_s=0.06,
                            jitter=0.0, max_attempts=50)
        t0 = time.monotonic()
        r = c.sort(a, deadline_ms=150)
        took = time.monotonic() - t0
        assert not r.ok and r.error == "deadline_exceeded"
        assert "client-side" in r.detail
        assert took < 2.0                       # bounded, not 50 retries
        assert c.stats["attempts"] < 50


def test_slow_drip_bounded_by_total_read_budget(rng):
    """A drip client whose every chunk 'makes progress' must still be
    shed at the TOTAL read budget (per-recv timeouts alone would never
    fire) — the review-found read1 contract."""
    with serve_core(SORT_SERVE_READ_TIMEOUT_S="0.5",
                    SORT_SERVE_BATCH_WINDOW_MS="0") as core:
        with wire_server(core) as port:
            n = 1 << 14
            hdr = json.dumps({"v": "sortserve.v1", "dtype": "int32",
                              "n": n}).encode() + b"\n"
            s = socket.create_connection(("127.0.0.1", port), timeout=10)
            s.sendall(hdr)
            t0 = time.monotonic()
            shed = False
            try:
                # 100 B every 120 ms: each recv succeeds well inside
                # any per-recv timeout; only the total budget binds
                for _ in range(40):
                    s.sendall(b"\x01" * 100)
                    time.sleep(0.12)
            except OSError:
                shed = True
            assert shed, "server never shed the drip"
            assert time.monotonic() - t0 < 3.0
            s.close()
            assert wait_until(
                lambda: core.admission.inflight_bytes == 0, 5.0)
            assert core.metrics.counter(
                "sort_serve_timeouts_total").get(kind="read") >= 1


def test_client_hedging_cuts_injected_tail(rng):
    """First connection's reply held 1s, second instant: the hedge
    fires at 0.1s and wins; the reply is fingerprint-verified."""
    a = rng.integers(-2**31, 2**31 - 1, size=300, dtype=np.int32)
    spanlog = SpanLog()
    with flaky_server([1.0, "ok", "ok"]) as port:
        c = ResilientClient("127.0.0.1", port, hedge_after_s=0.1,
                            read_timeout=10.0, spanlog=spanlog)
        t0 = time.perf_counter()
        r = c.sort(a, trace_id="hedge-unit")
        dt = time.perf_counter() - t0
        assert r.ok and np.array_equal(r.arr, np.sort(a))
        assert dt < 0.8, f"hedge did not cut the tail ({dt:.2f}s)"
        assert c.stats["hedges"] == 1 and c.stats["hedge_wins"] == 1
        hedge_spans = [s for s in spanlog.spans if s.name == "serve.hedge"]
        assert hedge_spans and hedge_spans[0].attrs["winner"] == "hedge"


def test_reply_fingerprint_rejects_foreign_bytes(rng):
    a = rng.integers(-2**31, 2**31 - 1, size=64, dtype=np.int32)
    good = ServeReply(True, {"ok": True}, np.sort(a))
    assert reply_fingerprint_ok(a, good)
    # truncation, reordering-with-substitution, and unsorted replies
    # all fail at least one of the three checks
    assert not reply_fingerprint_ok(a, ServeReply(True, {},
                                                  np.sort(a)[:-1]))
    substituted = np.sort(a).copy()
    substituted[0] = substituted[0] ^ 1      # sorted, but foreign bytes
    assert not reply_fingerprint_ok(a, ServeReply(True, {}, substituted))
    assert not reply_fingerprint_ok(a, ServeReply(False, {}))  # errors
    unsorted = np.sort(a)[::-1].copy()       # right multiset, bad order
    assert not reply_fingerprint_ok(a, ServeReply(True, {}, unsorted))


# ----------------------------------------------------------- chaos proxy

def test_chaos_proxy_torn_header_and_delay(rng):
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                           / "bench"))
    from wire_chaos import ChaosProxy

    a = rng.integers(-2**31, 2**31 - 1, size=200, dtype=np.int32)
    with flaky_server(["ok"]) as port:
        with ChaosProxy("127.0.0.1", port, "wire_torn_header@4") as px:
            with pytest.raises((OSError, ConnectionError)):
                ServeClient("127.0.0.1", px.port, timeout=5).sort(a)
            assert px.log[0] == (0, "wire_torn_header")
        # upstream server is untouched: direct request still works
        with ServeClient("127.0.0.1", port, timeout=5) as c:
            assert c.sort(a).ok
        with ChaosProxy("127.0.0.1", port,
                        "wire_delay_response@300:2") as px:
            with ServeClient("127.0.0.1", px.port, timeout=10) as c:
                t0 = time.perf_counter()
                assert c.sort(a).ok                 # conn 0: clean
                fast = time.perf_counter() - t0
            with ServeClient("127.0.0.1", px.port, timeout=10) as c:
                t0 = time.perf_counter()
                assert c.sort(a).ok                 # conn 1: delayed
                slow = time.perf_counter() - t0
            assert slow >= 0.28 > fast


# -------------------------------------------------------- knob contract

def test_lifecycle_knob_validation():
    cases = {
        "SORT_SERVE_IDLE_TIMEOUT_S": "0",
        "SORT_SERVE_READ_TIMEOUT_S": "-1",
        "SORT_SERVE_DISPATCH_TIMEOUT_S": "nan",
        "SORT_SERVE_BREAKER_BACKOFF_S": "x",
        "SORT_SERVE_COMPLETION_TIMEOUT_S": "0",
        "SORT_FAULT_STALL_MS": "0",
    }
    for name, bad in cases.items():
        with knobs.scoped_env(**{name: bad}):
            with pytest.raises(knobs.KnobError, match=name):
                knobs.get(name)
    with knobs.scoped_env(SORT_SERVE_DISPATCH_TIMEOUT_S="0"):
        assert knobs.get("SORT_SERVE_DISPATCH_TIMEOUT_S") == 0.0
