"""Pallas bitonic sort kernel tests (interpret mode on the CPU mesh).

Covers the three kernels (block sort / grouped cross / fused merge) at
every structural configuration: single-block, multi-block without cross
layers (nbits <= 3), and multi-block with grouped cross layers
(nbits > 3, the 2^26+ shape of the real thing), plus the padding path
and adversarial patterns.  Ground truth is ``np.sort``.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from mpitest_tpu.ops import bitonic


def _rand(n, rng):
    return rng.integers(0, 1 << 32, n, dtype=np.uint32)


@pytest.mark.parametrize("relayout", [True, False])
@pytest.mark.parametrize(
    "n_log2,b_log2",
    [
        # Interpret-mode cost scales with n (ISSUE 13 tier-1 budget):
        # every structural configuration keeps a cell, but the cross
        # layers ride the SMALLEST shape that reaches them (nbits =
        # n_log2 - b_log2 is what selects the schedule, not n itself);
        # the 2^18 8-member-visit shape moved to the `slow` tier.
        (10, 10),   # single block, minimum size
        (13, 13),   # single block
        (13, 10),   # 8 blocks: merge stages, no cross layers
        (14, 10),   # 16 blocks: one grouped cross layer
        (15, 10),   # 32 blocks: cross layers at two distances
        pytest.param(18, 11, marks=pytest.mark.slow),
        # ^ nbits up to 7: 8-member visits + 1/2-bit remainders — needs
        #   n >= 2^17 by construction (b_log2 floor is the VMEM tile),
        #   so it cannot shrink; deep runs (no -m 'not slow') keep it
    ],
)
def test_sort_padded(n_log2, b_log2, relayout):
    """Both cross schedules (round-5 relayout default and the round-4
    grouped-cross A/B baseline), incl. the 3-bit visit path."""
    rng = np.random.default_rng(n_log2 * 31 + b_log2)
    x = _rand(1 << n_log2, rng)
    out = bitonic.sort_padded(jnp.asarray(x), 1 << n_log2, b_log2,
                              interpret=True, relayout=relayout)
    np.testing.assert_array_equal(np.asarray(out), np.sort(x))


def _check_pairs(k, p, ks, ps):
    """Pair-engine contract: keys exactly sorted; the (key, payload)
    PAIR multiset is preserved (payloads may be permuted within an
    equal-key run — that is the documented contract; the 64-bit caller
    fixes runs afterwards)."""
    np.testing.assert_array_equal(ks, np.sort(k))
    got = np.stack([ks, ps], 1)
    want = np.stack([k, p], 1)
    np.testing.assert_array_equal(
        got[np.lexsort((got[:, 1], got[:, 0]))],
        want[np.lexsort((want[:, 1], want[:, 0]))],
    )


@pytest.mark.parametrize("relayout", [True, False])
@pytest.mark.parametrize(
    "n_log2,b_log2,span",
    [
        # Same budget contract as test_sort_padded: smallest shape per
        # structural class; odd (nbits=5) AND even (nbits=4) visit
        # counts stay covered, the 2^17 7-bit shape is `slow`-tier.
        (10, 10, 32),    # single block, heavy duplication
        (12, 12, 1 << 32),   # single block, full span
        (13, 10, 256),   # merge stages, duplicated keys
        (14, 10, 1 << 32),   # one grouped cross layer (even visits)
        (15, 10, 64),    # cross at two distances + dups (odd visits)
        pytest.param(17, 10, 1 << 32, marks=pytest.mark.slow),
        # ^ nbits up to 7: 8-member visits + 1/2-bit remainders
    ],
)
def test_sort_pairs_padded(n_log2, b_log2, span, relayout):
    """Both cross schedules: the round-5 rotation-relayout fused visits
    (default) and the round-4 single-layer path (the A/B baseline)."""
    rng = np.random.default_rng(n_log2 * 37 + b_log2)
    n = 1 << n_log2
    k = rng.integers(0, span, n).astype(np.uint32)
    p = rng.integers(0, 1 << 32, n, dtype=np.uint32)
    ks, ps = bitonic.sort_pairs_padded(jnp.asarray(k), jnp.asarray(p),
                                       n, b_log2, interpret=True,
                                       relayout=relayout)
    _check_pairs(k, p, np.asarray(ks), np.asarray(ps))


@pytest.mark.parametrize(
    "n_log2,b_log2",
    [(13, 10), pytest.param(16, 11, marks=pytest.mark.slow)],
)
def test_sort_pairs_padded_tail3(n_log2, b_log2):
    """The 3-bit merge tail (8-member rot-merge + 8-member contiguous
    merge at nbits=3) — priced on chip as session-dependent (BASELINE.md
    round 5), kept available behind ``tail_bits=3``."""
    rng = np.random.default_rng(n_log2)
    n = 1 << n_log2
    k = rng.integers(0, 1 << 32, n, dtype=np.uint32)
    p = rng.integers(0, 1 << 32, n, dtype=np.uint32)
    ks, ps = bitonic.sort_pairs_padded(jnp.asarray(k), jnp.asarray(p),
                                       n, b_log2, interpret=True,
                                       tail_bits=3)
    _check_pairs(k, p, np.asarray(ks), np.asarray(ps))


def test_fix_runs_pairs_kernel_and_boundary():
    """The in-VMEM run-fix kernel + XLA boundary strip must sort lo
    within every equal-hi run of length <= passes — including runs that
    CROSS block boundaries — matching the unique per-run-sorted answer
    (and hence the reference XLA formulation, kernels._fix_runs_oe)."""
    from mpitest_tpu.ops import kernels

    rng = np.random.default_rng(11)
    n, b_log2, passes = 1 << 13, 10, 8
    # runs of length 1..8 over strictly increasing hi values: many runs
    # straddle the 2^10 block boundaries
    lens = []
    total = 0
    while total < n:
        l = int(rng.integers(1, passes + 1))
        l = min(l, n - total)
        lens.append(l)
        total += l
    hi = np.repeat(np.arange(len(lens), dtype=np.uint32) * 11 + 3, lens)
    lo = rng.integers(0, 1 << 32, n, dtype=np.uint64).astype(np.uint32)

    got = bitonic.fix_runs_pairs(jnp.asarray(hi), jnp.asarray(lo), passes,
                                 b_log2, interpret=True)
    got = kernels._fix_boundary(jnp.asarray(hi), got, passes, 1 << b_log2)
    want = lo.copy()
    start = 0
    for l in lens:
        want[start:start + l] = np.sort(want[start:start + l])
        start += l
    np.testing.assert_array_equal(np.asarray(got), want)


@pytest.mark.parametrize("pattern", ["random", "sorted", "reversed",
                                     "all-equal", "few-distinct"])
def test_patterns(pattern):
    rng = np.random.default_rng(7)
    n = 1 << 13
    if pattern == "random":
        x = _rand(n, rng)
    elif pattern == "sorted":
        x = np.sort(_rand(n, rng))
    elif pattern == "reversed":
        x = np.sort(_rand(n, rng))[::-1].copy()
    elif pattern == "all-equal":
        x = np.full(n, 0xDEADBEEF, np.uint32)
    else:
        x = rng.integers(0, 5, n, dtype=np.uint32)
    out = bitonic.sort_padded(jnp.asarray(x), n, 11, interpret=True)
    np.testing.assert_array_equal(np.asarray(out), np.sort(x))


def test_extremes_and_sign_flip():
    """Values straddling the int32 sign bit sort in uint32 order (the
    kernel's internal int32 domain must not leak)."""
    rng = np.random.default_rng(3)
    x = np.concatenate([
        _rand((1 << 13) - 6, rng),
        np.asarray([0, 1, 0x7FFFFFFF, 0x80000000, 0x80000001, 0xFFFFFFFF],
                   np.uint32),
    ])
    out = bitonic.sort_padded(jnp.asarray(x), 1 << 13, 10, interpret=True)
    np.testing.assert_array_equal(np.asarray(out), np.sort(x))


@pytest.mark.parametrize("n", [5000, (1 << 13) - 1, (1 << 13) + 1])
def test_public_entry_pads(n, monkeypatch):
    """Non-power-of-two sizes pad with the max sentinel and slice back."""
    monkeypatch.setattr(bitonic, "MIN_SORT_LOG2", 8)
    monkeypatch.setattr(bitonic, "BLOCK_LOG2", 10)
    rng = np.random.default_rng(n)
    x = _rand(n, rng)
    out = bitonic.bitonic_sort_u32(jnp.asarray(x), interpret=True)
    assert out.shape == (n,)
    np.testing.assert_array_equal(np.asarray(out), np.sort(x))


def test_small_n_falls_back_to_lax():
    rng = np.random.default_rng(0)
    x = _rand(100, rng)
    out = bitonic.bitonic_sort_u32(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(out), np.sort(x))
