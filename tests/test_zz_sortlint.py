"""Tests for the static-analysis subsystem (ISSUE 4): sortlint rules on
good/bad fixture snippets, the knob registry's contracts, the span
schema, the comm parity checker, and the repo-wide dogfood run.

Named ``test_zz_*`` to sort LAST: tier-1 is timeout-bound and
dots-counted, and everything here is pure ast/text/registry work (no
jit compiles), so the whole module stays in low single-digit seconds.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from tools import comm_parity  # noqa: E402
from tools.sortlint import (  # noqa: E402
    LINT_VERSION, RULES, lint_repo, lint_source)

from mpitest_tpu.utils import knobs, span_schema  # noqa: E402


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------- fixtures

def test_sl001_env_read_flagged_writes_allowed():
    bad = "import os\nv = os.environ.get('SORT_ALGO', 'sample')\n"
    assert rules_of(lint_source(bad, "mpitest_tpu/x.py")) == ["SL001"]
    bad2 = "import os\nv = os.getenv('SORT_ALGO')\n"
    assert rules_of(lint_source(bad2, "x.py")) == ["SL001"]
    bad3 = "import os\nv = os.environ['SORT_ALGO']\n"
    assert rules_of(lint_source(bad3, "x.py")) == ["SL001"]
    # writes and subprocess-env construction stay legal
    good = ("import os\nos.environ['A'] = '1'\n"
            "os.environ.setdefault('B', '2')\ndel os.environ['A']\n"
            "env = dict(os.environ, C='3')\n")
    assert lint_source(good, "x.py") == []
    # the registry itself is exempt (it IS the sanctioned reader)
    assert lint_source(bad, "mpitest_tpu/utils/knobs.py") == []


def test_sl002_span_requires_with():
    bad = "s = tracer.spans.span('sort')\n"
    assert "SL002" in rules_of(lint_source(bad, "x.py"))
    good = "with tracer.spans.span('sort'):\n    pass\n"
    assert lint_source(good, "x.py") == []
    # wrapper idiom: returning the context manager is allowed
    wrapper = ("def f():\n    return spans.maybe_span('radix_pass')\n")
    assert lint_source(wrapper, "x.py") == []


def test_sl003_span_names_come_from_schema():
    bad = "with tracer.spans.span('totally_new_span'):\n    pass\n"
    assert rules_of(lint_source(bad, "x.py")) == ["SL003"]
    bad_phase = "with tracer.phase('warp'):\n    pass\n"
    assert rules_of(lint_source(bad_phase, "x.py")) == ["SL003"]
    good = ("with tracer.phase('sort'):\n"
            "    with tracer.spans.span('radix_pass'):\n        pass\n")
    assert lint_source(good, "x.py") == []
    nonliteral = "with tracer.spans.span(name):\n    pass\n"
    assert rules_of(lint_source(nonliteral, "x.py")) == ["SL003"]


def test_sl000_suppression_needs_reason():
    sup_ok = ("with tracer.spans.span(n):  "
              "# sortlint: disable=SL003 -- n is provably registered\n"
              "    pass\n")
    assert lint_source(sup_ok, "x.py") == []
    # a reasonless directive does NOT suppress: the original finding
    # survives and the directive itself is flagged
    sup_bad = ("with tracer.spans.span(n):  # sortlint: disable=SL003\n"
               "    pass\n")
    assert rules_of(lint_source(sup_bad, "x.py")) == ["SL000", "SL003"]


def test_sl004_metric_names_come_from_registry():
    bad = "metrics.counter('made_up_total').inc(1)\n"
    assert rules_of(lint_source(bad, "x.py")) == ["SL004"]
    # wrong kind for a registered name
    kind = "metrics.gauge('sort_serve_requests_total').set(1)\n"
    assert rules_of(lint_source(kind, "x.py")) == ["SL004"]
    nonlit = "metrics.counter(name).inc(1)\n"
    assert rules_of(lint_source(nonlit, "x.py")) == ["SL004"]
    good = ("self.metrics.counter('sort_serve_requests_total')"
            ".inc(1, status='ok')\n"
            "metrics.histogram('sort_serve_queue_wait_seconds')"
            ".observe(0.1)\n")
    assert lint_source(good, "x.py") == []
    # unrelated receivers never match (kernels.histogram is a jnp op)
    unrelated = "h = kernels.histogram(dest, n_ranks)\n"
    assert lint_source(unrelated, "x.py") == []
    # the registry module itself is exempt
    assert lint_source(bad, "mpitest_tpu/utils/metrics_live.py") == []


def test_sl005_plan_decisions_come_from_registry():
    bad = "plan.decide('warp_speed', chosen=1)\n"
    assert rules_of(lint_source(bad, "x.py")) == ["SL005"]
    bad2 = "self.plan.actual('made_up', need=3)\n"
    assert rules_of(lint_source(bad2, "x.py")) == ["SL005"]
    nonlit = "plan.bump(name, 'regrows')\n"
    assert rules_of(lint_source(nonlit, "x.py")) == ["SL005"]
    good = ("plan.decide('cap', chosen=128, cap=128, need=100)\n"
            "self.plan.bump('cap', 'regrows')\n"
            "plan.actual('restage', peer_ratio=1.1)\n")
    assert lint_source(good, "x.py") == []
    # unrelated receivers never match (a dict named `state`, say)
    unrelated = "state.decide('whatever')\n"
    assert lint_source(unrelated, "x.py") == []
    # the registry module itself is exempt
    assert lint_source(bad, "mpitest_tpu/models/plan.py") == []


def test_sl006_planner_policies_come_from_registry():
    bad = "p = planner.policy('warp_speed')\n"
    assert rules_of(lint_source(bad, "x.py")) == ["SL006"]
    bad2 = "planner_mod.policy('made_up')\n"
    assert rules_of(lint_source(bad2, "x.py")) == ["SL006"]
    # a dynamic lookup is allowed: policy() raises KeyError on
    # unregistered names at runtime — the call IS the registry check
    nonlit = "planner.policy(name)\n"
    assert lint_source(nonlit, "x.py") == []
    # the recorded verdict is policed too: plan.decide("planner",
    # chosen=...) must use a registered policy name
    bad3 = "plan.decide('planner', chosen='warp_speed', applied=True)\n"
    assert rules_of(lint_source(bad3, "x.py")) == ["SL006"]
    good = ("p = planner.policy('verify_passthrough')\n"
            "plan.decide('planner', chosen='window_auto', applied=True)\n"
            "plan.decide('planner', chosen=pchoice.policy)\n")
    assert lint_source(good, "x.py") == []
    # unrelated receivers never match
    unrelated = "cfg.policy('whatever')\n"
    assert lint_source(unrelated, "x.py") == []
    # the registry module itself is exempt
    assert lint_source(bad, "mpitest_tpu/models/planner.py") == []


def test_sl007_doctor_rules_come_from_registry():
    bad = "doctor.run_rule('warp_drive_misfire', ev)\n"
    assert rules_of(lint_source(bad, "x.py")) == ["SL007"]
    # alerts are policed on ANY receiver — the sentinel raises them
    bad2 = "self._alert('made_up', 'warn', 'x', value=1.0, threshold=1)\n"
    assert rules_of(lint_source(bad2, "x.py")) == ["SL007"]
    # serve.alert emissions carry a rule label that must be registered
    bad3 = "spans.record('serve.alert', 0.0, 0.0, rule='made_up')\n"
    assert rules_of(lint_source(bad3, "x.py")) == ["SL007"]
    # a computed name is allowed: run_rule/_alert raise KeyError on
    # unregistered names at runtime — the call IS the registry check
    nonlit = ("doctor.run_rule(name, ev)\n"
              "self._alert(rule, sev, msg, value=v, threshold=t)\n"
              "spans.record('serve.alert', 0.0, 0.0, rule=rule)\n")
    assert lint_source(nonlit, "x.py") == []
    good = ("doctor.run_rule('cap_thrash', ev)\n"
            "self._alert('deadline_burn', 'critical', 'x', value=3.0, "
            "threshold=2.0)\n"
            "spans.record('serve.alert', 0.0, 0.0, rule='skew_imbalance')\n")
    assert lint_source(good, "x.py") == []
    # unrelated receivers never match the run_rule shape
    unrelated = "router.run_rule('whatever', ev)\n"
    assert lint_source(unrelated, "x.py") == []
    # the registry module itself is exempt
    assert lint_source(bad, "mpitest_tpu/doctor.py") == []


def test_doctor_registry_vocabulary():
    from mpitest_tpu import doctor as doctor_mod

    assert all(doc for doc in doctor_mod.DOCTOR_RULES.values())
    assert {"skew_imbalance", "cap_thrash", "compile_storm",
            "window_misfit", "spill_bound",
            "verify_overhead_regression", "breaker_flap",
            "deadline_burn", "local_sort_lax",
            "spill_churn"} == set(doctor_mod.DOCTOR_RULES)
    # every vocabulary key has a registered diagnosis function
    assert set(doctor_mod.DOCTOR_RULES) == set(doctor_mod._RULES)
    assert all(s in doctor_mod.SEVERITIES
               for s in ("info", "warn", "critical"))


def test_planner_registry_vocabulary():
    from mpitest_tpu.models import planner as planner_mod

    assert all(doc for doc in planner_mod.PLANNER_POLICIES.values())
    for must in ("static", "verify_passthrough", "merge_sample",
                 "radix_narrow", "cap_margin", "window_auto"):
        assert must in planner_mod.PLANNER_POLICIES


def test_plan_registry_vocabulary():
    from mpitest_tpu.models import plan as plan_mod

    assert all(doc for doc in plan_mod.PLAN_DECISIONS.values())
    assert {"algo", "cap", "restage", "engine", "exchange_engine",
            "passes", "ladder", "batch",
            "planner", "external"} == set(plan_mod.PLAN_DECISIONS)


def test_metrics_registry_vocabulary():
    from mpitest_tpu.utils import metrics_live

    assert all(kind in ("counter", "gauge", "histogram") and doc
               for kind, doc in metrics_live.METRICS.values())
    # every histogram bucket set belongs to a registered histogram
    for name in metrics_live._HISTOGRAM_BUCKETS:
        assert metrics_live.METRICS[name][0] == "histogram"


def test_sl010_lax_reduce_banned():
    bad = "import jax\nout = jax.lax.reduce(x, 0, op, (0,))\n"
    assert rules_of(lint_source(bad, "x.py")) == ["SL010"]
    good = "import jax.numpy as jnp\nout = jnp.sum(x)\n"
    assert lint_source(good, "x.py") == []


def test_sl011_bare_device_put():
    bad = "import jax\ny = jax.device_put(x, dev)\n"
    assert rules_of(lint_source(bad, "x.py")) == ["SL011"]
    # ... except inside the guard's own definition
    good = ("def checked_device_put(x, t):\n"
            "    import jax\n    return jax.device_put(x, t)\n")
    assert lint_source(good, "x.py") == []


def test_sl012_host_sync_inside_traced_fn():
    bad = ("import jax\nimport numpy as np\n"
           "def f(x):\n    return np.asarray(x) + 1\n"
           "g = jax.jit(f)\n")
    assert "SL012" in rules_of(lint_source(bad, "x.py"))
    bad2 = ("import jax\n"
            "def f(x):\n    x.block_until_ready()\n    return x\n"
            "g = jax.jit(f)\n")
    assert "SL012" in rules_of(lint_source(bad2, "x.py"))
    # the same calls OUTSIDE traced functions are fine
    good = ("import numpy as np\n"
            "def h(x):\n    return np.asarray(x)\n")
    assert lint_source(good, "x.py") == []


def test_sl013_pallas_call_home_and_interpret():
    """ISSUE 13: pl.pallas_call lives only in mpitest_tpu/ops/, and the
    entry point around it must expose an `interpret=` parameter so the
    CPU parity gates can drive every kernel."""
    call = ("from jax.experimental import pallas as pl\n"
            "def launch(x: object, interpret: bool = False) -> object:\n"
            "    return pl.pallas_call(lambda r, o: None,\n"
            "                          interpret=interpret)(x)\n")
    # outside ops/: flagged wherever it sits
    assert rules_of(lint_source(call, "mpitest_tpu/models/x.py")) == ["SL013"]
    assert rules_of(lint_source(call, "bench/x.py")) == ["SL013"]
    # in ops/ with an interpret= entry-point parameter: clean
    assert lint_source(call, "mpitest_tpu/ops/x.py") == []
    # in ops/ but the entry point cannot be driven in interpret mode
    no_interp = ("from jax.experimental import pallas as pl\n"
                 "def launch(x):\n"
                 "    return pl.pallas_call(lambda r, o: None)(x)\n")
    assert rules_of(lint_source(no_interp, "mpitest_tpu/ops/x.py")) == \
        ["SL013"]
    # nested launcher inherits the outer entry point's parameter
    nested = ("from jax.experimental import pallas as pl\n"
              "def outer(x, interpret=False):\n"
              "    def inner(y):\n"
              "        return pl.pallas_call(lambda r, o: None,\n"
              "                              interpret=interpret)(y)\n"
              "    return inner(x)\n")
    assert lint_source(nested, "mpitest_tpu/ops/x.py") == []


def test_sl014_spill_file_fence():
    """ISSUE 15: run-file reads/writes live only in store/runs.py —
    ad-hoc open()/np.memmap of a spill artifact bypasses the SORTBIN1
    framing checks and the fingerprint sidecar fold."""
    lit = 'def f() -> None:\n    open("/tmp/spill/r0.run", "rb")\n'
    assert rules_of(lint_source(lit, "mpitest_tpu/serve/x.py")) == \
        ["SL014"]
    fstr = ('def f(d: str) -> None:\n'
            '    open(f"{d}/part.fpr.json")\n')
    assert rules_of(lint_source(fstr, "bench/x.py")) == ["SL014"]
    mm = ('import numpy as np\n'
          'def f(info: object) -> None:\n'
          '    np.memmap(info.pay_path, dtype=np.uint8)\n')
    assert rules_of(lint_source(mm, "mpitest_tpu/store/external.py")) \
        == ["SL014"]
    # the home module is exempt — it IS the fence
    assert lint_source(lit, "mpitest_tpu/store/runs.py") == []
    # unrelated open() stays legal everywhere
    ok = 'def f() -> None:\n    open("/tmp/keys.bin", "rb")\n'
    assert lint_source(ok, "mpitest_tpu/serve/x.py") == []


def test_sl014_manifest_journal_fence():
    """ISSUE 18: spill-manifest journals (.mfst) are fenced into
    store/manifest.py — the commit protocol (atomic begin, fsync'd
    appends, torn-tail replay) lives there, and runs.py is NOT a valid
    home for them either."""
    lit = 'def f() -> None:\n    open("/spill/ds1.mfst", "ab")\n'
    assert rules_of(lint_source(lit, "mpitest_tpu/serve/x.py")) == \
        ["SL014"]
    # runs.py is the RUN home, not the manifest home
    assert rules_of(lint_source(lit, "mpitest_tpu/store/runs.py")) == \
        ["SL014"]
    # the manifest home is exempt for .mfst ...
    assert lint_source(lit, "mpitest_tpu/store/manifest.py") == []
    # ... but not for run files
    run_open = 'def f() -> None:\n    open("/spill/r0.run", "rb")\n'
    assert rules_of(lint_source(
        run_open, "mpitest_tpu/store/manifest.py")) == ["SL014"]


def test_sl014_spill_rename_needs_replace():
    """ISSUE 18: publishing a spill artifact with os.rename (instead
    of os.replace) is a finding ANYWHERE, home modules included — the
    durable-commit protocol is replace + fsync(dir)."""
    bad = ('import os\n'
           'def f(d: str) -> None:\n'
           '    os.rename(f"{d}/r0.run.tmp", f"{d}/r0.run")\n')
    assert rules_of(lint_source(bad, "mpitest_tpu/store/runs.py")) == \
        ["SL014"]
    bad_m = ('import os\n'
             'def f(d: str) -> None:\n'
             '    os.rename(f"{d}/a.mfst.tmp", f"{d}/a.mfst")\n')
    assert rules_of(lint_source(
        bad_m, "mpitest_tpu/store/manifest.py")) == ["SL014"]
    # os.replace is the blessed publish; non-spill renames stay legal
    ok = ('import os\n'
          'def f(d: str) -> None:\n'
          '    os.replace(f"{d}/r0.run.tmp", f"{d}/r0.run")\n'
          '    os.rename(f"{d}/log.txt", f"{d}/log.old")\n')
    assert lint_source(ok, "mpitest_tpu/store/runs.py") == []


def test_sl040_typed_core_annotations():
    bad = "def f(x):\n    return x\n"
    path = "mpitest_tpu/models/newmod.py"
    assert rules_of(lint_source(bad, path)) == ["SL040"]
    good = "def f(x: int) -> int:\n    return x\n"
    assert lint_source(good, path) == []
    # nested defs (jit bodies) are exempt by design
    nested = ("def outer() -> object:\n"
              "    def f(x):\n        return x\n    return f\n")
    assert lint_source(nested, path) == []
    # ...and the same file outside the typed core is untouched
    assert lint_source(bad, "bench/newprobe.py") == []


# ------------------------------------------------------------- dogfood

def test_repo_lints_clean():
    """The acceptance gate, as a test: 0 findings over the whole repo.
    Pure ast — this is the expensive-looking assertion that actually
    runs in ~a second."""
    findings = lint_repo(REPO)
    assert findings == [], "\n".join(f.render() for f in findings)
    assert len(RULES) >= 10
    assert LINT_VERSION.startswith("sortlint.")


# ------------------------------------------------------ parity checker

def test_comm_parity_clean_and_catches_rank_conditional(tmp_path):
    assert comm_parity.main() == 0
    bad = tmp_path / "bad_sorter.c"
    bad.write_text(
        "void run(comm_ctx *c) {\n"
        "    int rank = comm_rank(c);\n"
        "    if (rank == 0) {\n"
        "        comm_barrier(c);\n"
        "    }\n"
        "}\n")
    findings = comm_parity.check_rank_conditional_collectives(bad)
    assert findings and "comm_barrier" in findings[0]
    ok = tmp_path / "ok_sorter.c"
    ok.write_text(
        "void run(comm_ctx *c) {\n"
        "    comm_barrier(c);\n"
        "    if (rank == 0) { printf(\"root\\n\"); }\n"
        "}\n")
    assert comm_parity.check_rank_conditional_collectives(ok) == []


def test_comm_parity_sequences_cover_both_sorters():
    seq_r = comm_parity.collective_sequence(REPO / "native" / "radix_sort.c")
    seq_s = comm_parity.collective_sequence(REPO / "native" / "sample_sort.c")
    assert seq_r[0] == "comm_bcast" and "comm_gatherv" in seq_r
    assert "comm_alltoallv" in seq_s


# ------------------------------------------------------- knob registry

def test_knob_registry_validation_contracts(monkeypatch):
    monkeypatch.setenv("SORT_MAX_RETRIES", "-1")
    with pytest.raises(ValueError, match="SORT_MAX_RETRIES"):
        knobs.get("SORT_MAX_RETRIES")
    monkeypatch.setenv("SORT_CAP_FACTOR", "nan")
    with pytest.raises(ValueError, match="finite number > 0"):
        knobs.get("SORT_CAP_FACTOR")
    monkeypatch.setenv("SORT_FALLBACK", "yes")
    with pytest.raises(ValueError, match="SORT_FALLBACK"):
        knobs.get("SORT_FALLBACK")
    monkeypatch.delenv("SORT_FALLBACK")
    assert knobs.get("SORT_FALLBACK") is True
    monkeypatch.setenv("BENCH_PLATFORM", "gpu:2")
    with pytest.raises(ValueError, match="cpu"):
        knobs.get("BENCH_PLATFORM")
    monkeypatch.setenv("BENCH_PLATFORM", "cpu:4")
    assert knobs.get("BENCH_PLATFORM") == 4
    # unregistered names are a hard error, not a silent None
    with pytest.raises(KeyError):
        knobs.get("SORT_NOT_A_KNOB")
    with pytest.raises(KeyError):
        knobs.get_raw("SORT_NOT_A_KNOB")


def test_knob_scoped_env_restores(monkeypatch):
    monkeypatch.setenv("SORT_ALGO", "radix")
    with knobs.scoped_env(SORT_ALGO="sample", SORT_RANKS="4"):
        assert knobs.get("SORT_ALGO") == "sample"
        assert knobs.get("SORT_RANKS") == 4
    assert knobs.get("SORT_ALGO") == "radix"
    assert knobs.get("SORT_RANKS") is None
    with knobs.scoped_env(SORT_ALGO=None):
        assert knobs.get("SORT_ALGO") == "sample"  # default when unset
    assert knobs.get("SORT_ALGO") == "radix"


def test_knob_reference_table_matches_readme():
    """README embeds the GENERATED table — drift fails here and in
    sortlint SL031."""
    table = knobs.reference_table()
    readme = (REPO / "README.md").read_text()
    for k in knobs.iter_knobs():
        assert f"`{k.name}`" in table
        assert f"`{k.name}`" in readme
    # the embedded block is byte-identical to the generator's output
    assert table in readme


def test_knob_cli_prints_table():
    out = subprocess.run(
        [sys.executable, "-m", "mpitest_tpu.utils.knobs"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0
    assert "| `SORT_ALGO` |" in out.stdout


# ---------------------------------------------------------- span schema

def test_span_schema_registry():
    assert span_schema.is_registered("sort")
    assert span_schema.is_registered("phase:verify")
    assert span_schema.is_registered("ingest.transfer")
    assert not span_schema.is_registered("phase:warp")
    assert not span_schema.is_registered("made_up")
    assert set(span_schema.INGEST_HOST_STAGES) <= set(span_schema.SPAN_NAMES)
    # every registered name carries a nonempty doc
    assert all(doc for doc in span_schema.SPAN_NAMES.values())


def test_report_flags_unregistered_span(tmp_path):
    from mpitest_tpu import report

    f = tmp_path / "t.jsonl"
    f.write_text('{"v": "span.v1", "name": "mystery", "id": 0, '
                 '"parent": null, "t0": 0.0, "dt": 0.1, "attrs": {}}\n')
    assert report.main(["--check", str(f)]) == 0
    assert report.main(["--check", "--require-registered-spans",
                        str(f)]) == 1


# ------------------------------------------------------ tooling state

def test_bench_row_tooling_state():
    import bench

    t = bench.tooling_state()
    assert t["sortlint"] == LINT_VERSION
    assert t["sortlint_rules"] == len(RULES)
    assert "-Wconversion" in t["cwarn"]
    assert "tsan" in t["sanitize"]
