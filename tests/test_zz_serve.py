"""Sort-as-a-service tests (ISSUE 8) — named to sort late (tier-1 is
timeout-bound): the segmented pack/split core, the AOT executor cache,
batching, typed backpressure, per-request fault isolation, and the
server driver's SIGTERM drain.

Most tests drive the transport-independent :class:`ServerCore`
in-process (the TCP layer is a thin framing shell over it, exercised by
``make serve-selftest`` plus one subprocess drill here)."""

from __future__ import annotations

import threading
from contextlib import contextmanager

import numpy as np
import pytest

from mpitest_tpu.models import segmented as sg
from mpitest_tpu.serve.admission import AdmissionControl, AdmissionReject
from mpitest_tpu.serve.executor_cache import ExecutorCache
from mpitest_tpu.utils import knobs
from mpitest_tpu.utils.spans import SpanLog


@contextmanager
def serve_core(**env):
    """A ServerCore configured via scoped knobs; its dispatch thread is
    stopped at exit so tests never leak threads into the suite."""
    from mpitest_tpu.serve.server import ServerCore

    with knobs.scoped_env(**env):
        core = ServerCore()
        try:
            yield core
        finally:
            core.batcher.stop(timeout=10)


# ------------------------------------------------------- segmented core

def test_bucket_for_power_of_two():
    assert sg.bucket_for(1) == sg.MIN_BUCKET
    assert sg.bucket_for(sg.MIN_BUCKET) == sg.MIN_BUCKET
    assert sg.bucket_for(sg.MIN_BUCKET + 1) == 2 * sg.MIN_BUCKET
    assert sg.bucket_for(3000) == 4096
    assert sg.bucket_for(4096) == 4096
    with pytest.raises(ValueError):
        sg.bucket_for(-1)


def test_pack_sort_split_bit_parity(rng):
    """The packed multi-tenant dispatch must be bit-identical to
    sorting each request alone — the acceptance contract."""
    arrs = [rng.integers(-2**31, 2**31 - 1, size=n, dtype=np.int32)
            for n in (307, 1, 900, 64)]
    batch = sg.pack_segments(arrs, np.dtype(np.int32))
    sorted_words = sg.run_packed(batch)
    outs = sg.split_segments(batch, sorted_words)
    for a, o in zip(arrs, outs):
        assert np.array_equal(o, np.sort(a))
    assert all(sg.verify_segments(batch, sorted_words))


def test_pack_sort_split_parity_uint64(rng):
    """Wider (2-word) keys ride the variadic lowering — same contract."""
    arrs = [rng.integers(0, 2**64, size=n, dtype=np.uint64)
            for n in (150, 40)]
    batch = sg.pack_segments(arrs, np.dtype(np.uint64))
    outs = sg.split_segments(batch, sg.run_packed(batch))
    for a, o in zip(arrs, outs):
        assert np.array_equal(o, np.sort(a))


def test_verify_flags_only_the_corrupt_segment(rng):
    arrs = [rng.integers(-2**31, 2**31 - 1, size=256, dtype=np.int32)
            for _ in range(3)]
    batch = sg.pack_segments(arrs, np.dtype(np.int32))
    sw = tuple(w.copy() for w in sg.run_packed(batch))
    sw[1][batch.offsets[1]] ^= 0x40        # corrupt one key of segment 1
    assert sg.verify_segments(batch, sw) == [True, False, True]


def test_pack_rejects_overflow(rng):
    a = rng.integers(-100, 100, size=600, dtype=np.int32)
    with pytest.raises(ValueError, match="bucket"):
        sg.pack_segments([a, a], np.dtype(np.int32), bucket=1024)


# ------------------------------------------------------- executor cache

def test_executor_cache_hit_miss_and_bucket_reuse():
    log = SpanLog()
    cache = ExecutorCache(log)
    # two different request totals land in ONE bucket -> one compile
    b1 = sg.bucket_for(300)
    b2 = sg.bucket_for(900)
    assert b1 == b2
    cache.get_packed(b1, "int32", 2)
    cache.get_packed(b2, "int32", 2)
    assert cache.stats.misses == 1 and cache.stats.hits == 1
    # a different bucket is a new entry
    cache.get_packed(sg.bucket_for(5000), "int32", 2)
    assert cache.stats.misses == 2
    events = [s for s in log.spans if s.name == "serve.compile_cache"]
    assert [e.attrs["hit"] for e in events] == [False, True, False]
    assert events[0].attrs["compile_s"] >= 0.0


def test_executor_cache_prewarm_cpu():
    cache = ExecutorCache()
    built = cache.prewarm((1024, 2048), ("int32",))
    assert built == 2
    assert cache.stats.buckets == {1024, 2048}
    # traffic into a prewarmed bucket never compiles
    cache.get_packed(1024, "int32", 2)
    assert cache.stats.hits == 1


# ---------------------------------------------------- admission control

def test_admission_typed_rejections():
    adm = AdmissionControl(max_inflight=2, max_bytes=1000)
    adm.admit(400)
    adm.admit(400)
    with pytest.raises(AdmissionReject) as e:
        adm.admit(10)          # count bound first
    assert e.value.reason == "inflight"
    adm.release(400)
    with pytest.raises(AdmissionReject) as e:
        adm.admit(700)         # byte bound
    assert e.value.reason == "bytes"
    adm.start_drain()
    with pytest.raises(AdmissionReject) as e:
        adm.admit(1)
    assert e.value.reason == "draining"
    adm.release(400)
    assert adm.wait_idle(timeout=1.0)


# ----------------------------------------------------------- ServerCore

def test_core_batches_concurrent_requests(rng):
    with serve_core(SORT_SERVE_BATCH_WINDOW_MS="60") as core:
        arrs = [rng.integers(-2**31, 2**31 - 1, size=400, dtype=np.int32)
                for _ in range(5)]
        results: dict = {}

        def send(i):
            results[i] = core.execute(arrs[i])

        threads = [threading.Thread(target=send, args=(i,))
                   for i in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, a in enumerate(arrs):
            st, out, _attrs = results[i]
            assert st == "ok"
            assert np.array_equal(out, np.sort(a))
        # the 60 ms window must have packed at least one multi-segment
        # batch out of 5 concurrent closed-loop arrivals
        assert any(r[2].get("batched") for r in results.values())
        assert core.batcher.batches < 5


def test_core_routes_large_requests_solo(rng, mesh8):
    with serve_core(SORT_SERVE_BATCH_KEYS="512") as core:
        a = rng.integers(-2**31, 2**31 - 1, size=2000, dtype=np.int32)
        st, out, attrs = core.execute(a)
        assert st == "ok" and np.array_equal(out, np.sort(a))
        assert attrs["batched"] is False


def test_core_backpressure_typed(rng):
    with serve_core(SORT_SERVE_MAX_INFLIGHT="1",
                    SORT_SERVE_BATCH_WINDOW_MS="30") as core:
        statuses = []

        def send(_):
            a = rng.integers(-2**31, 2**31 - 1, size=256, dtype=np.int32)
            statuses.append(core.execute(a)[0])

        threads = [threading.Thread(target=send, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert "backpressure" in statuses and "ok" in statuses
        assert set(statuses) <= {"backpressure", "ok"}
        # the server keeps serving after the burst
        a = rng.integers(-2**31, 2**31 - 1, size=64, dtype=np.int32)
        assert core.execute(a)[0] == "ok"


def test_per_request_fault_isolation(rng, mesh8):
    """A poisoned request (per-request SORT_FAULTS spec, test mode)
    yields a TYPED error; the next request on the same server
    succeeds — fault isolation, never server death."""
    with serve_core(SORT_SERVE_ALLOW_FAULTS="1", SORT_FALLBACK="0",
                    SORT_MAX_RETRIES="0") as core:
        a = rng.integers(-2**31, 2**31 - 1, size=2048, dtype=np.int32)
        st, detail, _ = core.execute(a, faults_spec="result_swap:inf")
        assert st == "integrity", (st, detail)
        st2, out, _ = core.execute(a)
        assert st2 == "ok" and np.array_equal(out, np.sort(a))


def test_batch_fault_isolated_to_segment(rng, mesh8):
    """Server-level SORT_FAULTS corrupting a packed batch result must
    flag only the touched segments; those re-run solo under the
    supervisor and every tenant still gets a verified result."""
    with serve_core(SORT_FAULTS="result_swap:1",
                    SORT_SERVE_BATCH_WINDOW_MS="60") as core:
        arrs = [rng.integers(-2**31, 2**31 - 1, size=500, dtype=np.int32)
                for _ in range(4)]
        results: dict = {}

        def send(i):
            results[i] = core.execute(arrs[i])

        threads = [threading.Thread(target=send, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, a in enumerate(arrs):
            st, out, _attrs = results[i]
            assert st == "ok"
            assert np.array_equal(out, np.sort(a))
        assert core.tracer.counters.get("serve_segment_requeues", 0) >= 1


# -------------------------------------------------------- knob contract

def test_serve_knob_validation():
    cases = {
        "SORT_SERVE_PORT": "70000",
        "SORT_SERVE_MAX_INFLIGHT": "0",
        "SORT_SERVE_MAX_BYTES": "x",
        "SORT_SERVE_BATCH_WINDOW_MS": "-1",
        "SORT_SERVE_BATCH_KEYS": "none",
        "SORT_SERVE_SHAPE_BUCKETS": "10,zap",
        "SORT_SERVE_PREWARM": "yes",
        "SORT_SERVE_ALLOW_FAULTS": "2",
    }
    for name, bad in cases.items():
        with knobs.scoped_env(**{name: bad}):
            with pytest.raises(knobs.KnobError, match=name):
                knobs.get(name)
    with knobs.scoped_env(SORT_SERVE_SHAPE_BUCKETS="14,10,14"):
        assert knobs.get("SORT_SERVE_SHAPE_BUCKETS") == (10, 14)


# ------------------------------------------------------- topology probe

def test_topology_probe_bounded_and_cached(monkeypatch):
    import subprocess as sp

    from mpitest_tpu.utils import topology_probe as tp

    tp.reset_cache()
    calls = []

    def fake_run(*a, **kw):
        calls.append(1)
        raise sp.TimeoutExpired(cmd="probe", timeout=kw.get("timeout"))

    monkeypatch.setattr(tp.subprocess, "run", fake_run)
    reason = tp.probe_tpu_compiler(timeout_s=1.0)
    assert "timed out" in reason
    # the verdict is cached: no second child process
    assert tp.probe_tpu_compiler() == reason
    assert len(calls) == 1
    tp.reset_cache()


# ------------------------------------------- wire + SIGTERM drain drill

def test_server_driver_wire_and_sigterm_drain(tmp_path):
    """The full subprocess contract: listening line, a wire round trip,
    a typed bad-request error, then SIGTERM -> graceful drain, exit 0.
    One subprocess (slow jax import) covers all of it."""
    import json
    import os
    import re
    import signal
    import subprocess
    import sys

    from mpitest_tpu.serve.client import ServeClient

    trace = tmp_path / "trace.jsonl"
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1",
               SORT_SERVE_PORT="0",
               SORT_SERVE_SHAPE_BUCKETS="10",
               SORT_TRACE=str(trace))
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, os.path.join(repo, "drivers", "sort_server.py")],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env)
    try:
        assert proc.stdout is not None
        line = proc.stdout.readline()
        m = re.search(r"listening on [\d.]+:(\d+)", line)
        assert m, f"no listening line: {line!r}"
        port = int(m.group(1))
        rng = np.random.default_rng(3)
        x = rng.integers(-2**31, 2**31 - 1, size=700, dtype=np.int32)
        with ServeClient("127.0.0.1", port) as c:
            r = c.sort(x, trace_id="wire-drill-1")
            assert r.ok and np.array_equal(r.arr, np.sort(x))
            # the wire layer echoes the client-minted trace id (ISSUE 10)
            assert r.trace_id == "wire-drill-1"
            # typed error, connection survives, next request works
            bad = c.sort(np.arange(8, dtype=np.int32), algo="bogus")
            assert not bad.ok and bad.error == "bad_request"
            r2 = c.sort(x)
            assert r2.ok
            # a trace id is minted when the client supplies... the
            # client always supplies one; the echo must be non-empty
            assert r2.trace_id
            # garbage trace ids are a typed wire error
            bad_tid = c.sort(x, trace_id="spaces are not ok")
            assert not bad_tid.ok and bad_tid.error == "bad_request"
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
        assert rc == 0, proc.stderr.read()[-1000:]
        spans = [json.loads(ln) for ln in trace.read_text().splitlines()]
        names = {s["name"] for s in spans}
        assert "serve.request" in names and "serve.batch" in names
    finally:
        if proc.poll() is None:
            proc.kill()
