"""Multi-host bring-up smoke tests (VERDICT r3 #6).

``multihost_init`` is the v5e-16 entry point (``parallel/mesh.py``): the
TPU-native ``MPI_Init``-across-nodes.  A real two-host launch needs two
hosts, but the coordinator handshake, process-id plumbing and the
mesh-after-init path all execute single-process — that is what runs here
(in a subprocess: ``jax.distributed.initialize`` must precede the first
backend query, which pytest's own JAX import has long passed).
"""

import os
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_multihost_init_noop_single_process():
    """No arguments = the common single-process case: must be a no-op
    (callable any number of times, no distributed runtime spun up)."""
    from mpitest_tpu.parallel import multihost_init

    multihost_init()
    multihost_init()


def test_multihost_init_validates_args_fail_fast():
    """ISSUE 3 satellite: malformed coordinator/process arguments must
    raise a clear ValueError IMMEDIATELY — before this change they
    reached jax.distributed.initialize and surfaced as a deep hang or
    an opaque traceback minutes into the handshake."""
    import pytest

    from mpitest_tpu.parallel import multihost_init

    # partial configuration: always a launcher bug
    with pytest.raises(ValueError, match="missing: num_processes"):
        multihost_init("127.0.0.1:9999")
    with pytest.raises(ValueError, match="missing: coordinator"):
        multihost_init(num_processes=2, process_id=0)
    with pytest.raises(ValueError, match="missing:"):
        multihost_init(process_id=1)
    # malformed coordinator address — including port-less IPv6-style
    # typos ('::1', 'fe80::1'), which rpartition alone would wave
    # through as host=':'+port='1' (review regression)
    for bad in ("coordinatorhost", ":1234", "host:", "host:notaport",
                "host:0", "host:70000", "::1", "fe80::1"):
        with pytest.raises(ValueError, match="coordinator"):
            multihost_init(bad, num_processes=2, process_id=0)
    # out-of-range process topology
    with pytest.raises(ValueError, match="num_processes"):
        multihost_init("h:1234", num_processes=0, process_id=0)
    with pytest.raises(ValueError, match="process_id"):
        multihost_init("h:1234", num_processes=2, process_id=2)
    with pytest.raises(ValueError, match="process_id"):
        multihost_init("h:1234", num_processes=2, process_id=-1)


def test_multihost_init_executes():
    """``multihost_init`` actually EXECUTES ``jax.distributed.initialize``
    (coordinator bind + handshake with itself, num_processes=1) and the
    framework sorts on a mesh brought up through it."""
    port = _free_port()
    code = textwrap.dedent(f"""
        import jax
        jax.config.update("jax_platforms", "cpu")
        from mpitest_tpu.parallel import multihost_init
        from mpitest_tpu.parallel.mesh import make_mesh
        multihost_init("127.0.0.1:{port}", num_processes=1, process_id=0)
        assert jax.process_count() == 1, jax.process_count()
        import numpy as np
        from mpitest_tpu.models.api import sort
        x = np.arange(1000, dtype=np.int32)[::-1].copy()
        got = sort(x, algorithm="radix", mesh=make_mesh())
        assert np.array_equal(got, np.arange(1000, dtype=np.int32))
        jax.distributed.shutdown()
        print("MULTIHOST_OK", jax.process_count())
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=f"{REPO}{os.pathsep}" + os.environ.get("PYTHONPATH", ""))
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=300, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "MULTIHOST_OK 1" in r.stdout
